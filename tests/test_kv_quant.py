"""Quantized KV serving — int8/int4 paged pools with per-block scales.

Three layers of coverage:

* **kernel parity** (interpret mode): the quantized Pallas
  decode/append variants vs the dense-gather fallback (the shipping CPU
  path inside ``block_multihead_attention``) — outputs to online-softmax
  tolerance, updated pools AND scale arrays bit-exact, including block
  boundaries (len % bs in {0, 1, bs-1}), GQA, the in-kernel scale update
  on fused writes, q_lens=0 window degeneracy, and int4 odd-D nibble
  padding (kernel-only: the op can't disambiguate odd head dims).
* **capacity**: an int8 (int4) pool fits >= 1.9x (>= 3.5x) the bf16
  block count at equal HBM bytes — asserted off the engines' real buffer
  nbytes (payload + scales), the PR's acceptance arithmetic.
* **engine composition**: quantized pool x {prefix cache, stride-k
  multi-step, legacy scheduler, speculative verify, multi-LoRA, TP mesh,
  supervised reset} — token-EXACT where quantization commutes with the
  feature (same quantized bytes either way), drift-BOUNDED where it
  cannot (speculative rollback re-rounds block scales; documented in
  docs/architecture.md), plus recorder/telemetry plumbing and the bench
  A/B smoke. ``kv_cache_dtype=None`` stays bit-identical to the
  pre-quantization engine (same traced programs — regression-tested
  against a plain bf16-pool engine).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import functional as IF
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.ops.kernels.paged_attention import (
    KV_QMAX, kv_block_scale, kv_pack, kv_packed_dim, kv_quantize,
    kv_unpack, paged_attention_append, paged_attention_decode)

CFG = LlamaConfig(vocab_size=96, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=4, max_position_embeddings=128)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(7)
    m = LlamaForCausalLM(CFG)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(0)
    return [rng.integers(1, 96, size=(n,)).astype(np.int32)
            for n in (13, 9)]


def _kw(**over):
    kw = dict(max_batch=2, max_seq_len=64, chunk_size=16,
              cache_impl="paged", block_size=8, scheduler="fused",
              kv_cache_dtype="int8")
    kw.update(over)
    return kw


def _toks(eng, prompts, n=10):
    return [o.token_ids for o in eng.generate(prompts, max_new_tokens=n)]


def _match_prefix(a, b):
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


# ---------------------------------------------------------------------------
# kernel parity (interpret mode) vs the dense fallback
# ---------------------------------------------------------------------------

def _quant_pools(rng, lens, grow, Hkv, D, BS, quant):
    """Quantized pools + tables covering ``lens`` (+``grow`` positions
    each), physical blocks shuffled, the trailing block reserved as the
    engine's scratch (never assigned — fallback drops what the kernel
    parks there)."""
    B = len(lens)
    need = [(int(L) + max(int(g), 1)) // BS + 1
            for L, g in zip(lens, grow)]
    MB = max(need) + 1
    NB = sum(need) + 2
    order = rng.permutation(NB - 1)
    tables = np.full((B, MB), -1, np.int32)
    it = iter(order)
    for b in range(B):
        for j in range(need[b]):
            tables[b, j] = next(it)
    kf = rng.standard_normal((NB, Hkv, BS, D)).astype(np.float32)
    vf = rng.standard_normal((NB, Hkv, BS, D)).astype(np.float32)
    ks = np.asarray(kv_block_scale(jnp.asarray(kf), quant, (2, 3)))
    vs = np.asarray(kv_block_scale(jnp.asarray(vf), quant, (2, 3)))
    kc = np.asarray(kv_quantize(jnp.asarray(kf),
                                jnp.asarray(ks)[..., None, None], quant))
    vc = np.asarray(kv_quantize(jnp.asarray(vf),
                                jnp.asarray(vs)[..., None, None], quant))
    return kc, vc, ks, vs, tables, np.asarray(lens, np.int32)


@pytest.mark.parametrize("quant", ["int8", "int4"])
@pytest.mark.parametrize("group", [1, 2])
def test_decode_kernel_parity(rng, quant, group):
    """Quantized decode kernel vs the dense fallback (public op), block
    boundaries len % bs in {0, 1, bs-1}, GQA: outputs to online-softmax
    tolerance, updated pools and scales BIT-exact (the scratch block may
    differ: the fallback drops -1-target writes, the kernel parks
    them)."""
    Hkv, D, BS = 2, 32, 8
    Hq = Hkv * group
    lens = [16, 17, 7, 3]
    kc, vc, ks, vs, tables, lens_ = _quant_pools(
        rng, lens, [1] * 4, Hkv, D, BS, quant)
    B = len(lens)
    q = rng.standard_normal((B, Hq, D)).astype(np.float32)
    knew = rng.standard_normal((B, Hkv, D)).astype(np.float32)
    vnew = rng.standard_normal((B, Hkv, D)).astype(np.float32)
    qkv = np.concatenate([q.reshape(B, -1), knew.reshape(B, -1),
                          vnew.reshape(B, -1)], -1)
    res = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kc), paddle.to_tensor(vc),
        None, paddle.to_tensor(lens_), None,
        block_tables=paddle.to_tensor(tables),
        cache_k_quant_scales=paddle.to_tensor(ks),
        cache_v_quant_scales=paddle.to_tensor(vs),
        cache_quant_type=quant)
    ro, rkc, rvc, rks, rvs = [np.asarray(t._value) for t in res]
    out, kc2, vc2, ks2, vs2 = paged_attention_decode(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(tables), jnp.asarray(lens_),
        new_k=jnp.asarray(knew), new_v=jnp.asarray(vnew),
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs), quant=quant)
    np.testing.assert_allclose(np.asarray(out), ro.reshape(B, Hq, D),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(kc2)[:-1], rkc[:-1])
    np.testing.assert_array_equal(np.asarray(vc2)[:-1], rvc[:-1])
    # scales to 1-ulp: the kernel reduces one [bs, D] block per grid
    # step, the fallback one whole-pool reduce — f32 ordering may differ
    np.testing.assert_allclose(np.asarray(ks2)[:-1], rks[:-1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vs2)[:-1], rvs[:-1], rtol=1e-6)


@pytest.mark.parametrize("quant", ["int8", "int4"])
def test_append_kernel_parity(rng, quant):
    """Quantized append kernel vs the dense fallback: q_lens covering
    {0 (idle slot), 1 (decode-shaped), mid, full chunk}, windows
    crossing block boundaries; pools + scales bit-exact, valid output
    rows to tolerance."""
    Hkv, D, BS, S = 2, 32, 8, 8
    Hq = 4
    lens = [16, 17, 7, 3]
    q_lens = np.asarray([0, 1, 5, 8], np.int32)
    kc, vc, ks, vs, tables, lens_ = _quant_pools(
        rng, lens, q_lens, Hkv, D, BS, quant)
    B = len(lens)
    qa = rng.standard_normal((B, S, Hq, D)).astype(np.float32)
    ka = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    va = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    qkv3 = np.concatenate([qa.reshape(B, S, -1), ka.reshape(B, S, -1),
                           va.reshape(B, S, -1)], -1)
    res = IF.block_multihead_attention(
        paddle.to_tensor(qkv3), paddle.to_tensor(kc), paddle.to_tensor(vc),
        None, paddle.to_tensor(lens_), paddle.to_tensor(q_lens),
        block_tables=paddle.to_tensor(tables),
        cache_k_quant_scales=paddle.to_tensor(ks),
        cache_v_quant_scales=paddle.to_tensor(vs),
        cache_quant_type=quant)
    ro3, rkc3, rvc3, rks3, rvs3 = [np.asarray(t._value) for t in res]
    out3, kc3, vc3, ks3, vs3 = paged_attention_append(
        jnp.asarray(qa), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(tables), jnp.asarray(lens_), jnp.asarray(q_lens),
        jnp.asarray(ka), jnp.asarray(va),
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs), quant=quant)
    ro3 = ro3.reshape(B, S, Hq, D)
    o3 = np.asarray(out3)
    for b in range(B):
        n = int(q_lens[b])
        if n:
            np.testing.assert_allclose(o3[b, :n], ro3[b, :n],
                                       atol=2e-5, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(kc3)[:-1], rkc3[:-1])
    np.testing.assert_array_equal(np.asarray(vc3)[:-1], rvc3[:-1])
    np.testing.assert_allclose(np.asarray(ks3)[:-1], rks3[:-1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vs3)[:-1], rvs3[:-1], rtol=1e-6)


def test_scale_update_on_fused_write(rng):
    """A new token whose magnitude dwarfs the block's content must GROW
    the written block's scale in-kernel (fresh absmax over the merged
    block) and saturate the stored int row at the grid edge."""
    quant = "int8"
    Hkv, D, BS = 2, 32, 8
    lens = [11]
    kc, vc, ks, vs, tables, lens_ = _quant_pools(
        rng, lens, [1], Hkv, D, BS, quant)
    knew = np.full((1, Hkv, D), 50.0, np.float32)   # >> unit-normal pool
    vnew = rng.standard_normal((1, Hkv, D)).astype(np.float32)
    q = rng.standard_normal((1, Hkv, D)).astype(np.float32)
    out, kc2, vc2, ks2, vs2 = paged_attention_decode(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(tables), jnp.asarray(lens_),
        new_k=jnp.asarray(knew), new_v=jnp.asarray(vnew),
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs), quant=quant)
    blk = int(tables[0, lens[0] // BS])
    slot = lens[0] % BS
    ks2 = np.asarray(ks2)
    np.testing.assert_allclose(ks2[blk], 50.0 / KV_QMAX[quant], rtol=1e-6)
    assert (ks2[blk] > ks[blk]).all()
    row = np.asarray(kc2)[blk, :, slot]             # [Hkv, D] ints
    np.testing.assert_array_equal(row, np.full_like(row, 127))
    # untouched blocks keep their exact payload + scale
    others = [i for i in range(kc.shape[0]) if i != blk]
    np.testing.assert_array_equal(np.asarray(kc2)[others], kc[others])
    np.testing.assert_array_equal(ks2[others], ks[others])


def test_dirty_block_reuse_does_not_inflate_scale(rng):
    """A freed block is re-handed WITHOUT zeroing: its stale content can
    be orders of magnitude above the new owner's values. The fused
    write's absmax must ignore the dead tail (positions past the new
    token) — otherwise the stale garbage inflates the block scale and
    quantizes the live row to zero, making greedy output depend on
    pool-reuse history. Kernel AND fallback: scale == the live row's
    own absmax, dequantized row ~= the written token."""
    quant = "int8"
    Hkv, D, BS = 2, 32, 8
    lens = [8]                      # new token opens block 1 at row 0
    kc, vc, ks, vs, tables, lens_ = _quant_pools(
        rng, lens, [1], Hkv, D, BS, quant)
    # dirty the target block with huge stale content (magnitude ~100)
    kc, ks = kc.copy(), ks.copy()
    blk = int(tables[0, 1])
    stale = 100.0 * rng.standard_normal((Hkv, BS, D)).astype(np.float32)
    ks[blk] = np.abs(stale).max(axis=(1, 2)) / KV_QMAX[quant]
    kc[blk] = np.asarray(kv_quantize(jnp.asarray(stale),
                                     jnp.asarray(ks[blk])[:, None, None],
                                     quant))
    knew = np.full((1, Hkv, D), 0.01, np.float32)
    vnew = rng.standard_normal((1, Hkv, D)).astype(np.float32)
    q = rng.standard_normal((1, Hkv, D)).astype(np.float32)
    out, kc2, vc2, ks2, vs2 = paged_attention_decode(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(tables), jnp.asarray(lens_),
        new_k=jnp.asarray(knew), new_v=jnp.asarray(vnew),
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs), quant=quant)
    ks2 = np.asarray(ks2)
    np.testing.assert_allclose(ks2[blk], 0.01 / KV_QMAX[quant],
                               rtol=1e-6)
    deq = np.asarray(kc2)[blk, :, 0].astype(np.float32) * ks2[blk][:, None]
    np.testing.assert_allclose(deq, 0.01, rtol=0.02)
    # dead tail rows stored zeroed (reuse history erased)
    assert not np.asarray(kc2)[blk, :, 1:].any()
    # fallback applies the identical rule (public op)
    qkv = np.concatenate([q.reshape(1, -1), knew.reshape(1, -1),
                          vnew.reshape(1, -1)], -1)
    res = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kc), paddle.to_tensor(vc),
        None, paddle.to_tensor(lens_), None,
        block_tables=paddle.to_tensor(tables),
        cache_k_quant_scales=paddle.to_tensor(ks),
        cache_v_quant_scales=paddle.to_tensor(vs),
        cache_quant_type=quant)
    np.testing.assert_array_equal(np.asarray(res[1]._value)[blk],
                                  np.asarray(kc2)[blk])
    np.testing.assert_allclose(np.asarray(res[3]._value)[blk], ks2[blk],
                               rtol=1e-6)


def test_int4_odd_d_padding(rng):
    """int4 nibble packing with an ODD head dim: pack/unpack round-trips
    the split-half layout (pad nibble sliced off), and the decode kernel
    attends dequantized odd-D pools correctly (read-only call vs a NumPy
    reference over the dequantized gather)."""
    D = 5
    vals = rng.integers(-7, 8, size=(4, 3, D)).astype(np.int32)
    packed = np.asarray(kv_pack(jnp.asarray(vals), "int4"))
    assert packed.shape == (4, 3, kv_packed_dim(D, "int4"))
    back = np.asarray(kv_unpack(jnp.asarray(packed), "int4", D))
    np.testing.assert_array_equal(back, vals.astype(np.float32))

    Hkv, BS = 2, 8
    lens = [9]
    kc, vc, ks, vs, tables, lens_ = _quant_pools(
        rng, lens, [1], Hkv, D, BS, "int4")
    q = rng.standard_normal((1, Hkv, D)).astype(np.float32)
    out = paged_attention_decode(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(tables), jnp.asarray(lens_),
        k_scale=jnp.asarray(ks), v_scale=jnp.asarray(vs), quant="int4")
    # NumPy reference on the dequantized logical sequence
    kf = np.asarray(kv_unpack(jnp.asarray(kc), "int4", D)) * \
        ks[..., None, None]
    vf = np.asarray(kv_unpack(jnp.asarray(vc), "int4", D)) * \
        vs[..., None, None]
    T = lens[0] + 1
    seq_k = np.concatenate([kf[tables[0, j]] for j in range(2)],
                           axis=1)[:, :T]           # [Hkv, T, D]
    seq_v = np.concatenate([vf[tables[0, j]] for j in range(2)],
                           axis=1)[:, :T]
    logits = np.einsum("hd,htd->ht", q[0], seq_k) / np.sqrt(D)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("ht,htd->hd", p, seq_v)
    np.testing.assert_allclose(np.asarray(out)[0], ref, atol=2e-5,
                               rtol=1e-4)


# ---------------------------------------------------------------------------
# capacity: blocks at equal HBM bytes (the acceptance arithmetic)
# ---------------------------------------------------------------------------

def test_pool_capacity_ratios():
    """An int8 (int4) pool fits >= 1.9x (>= 3.5x) the bf16 block count
    at equal HBM bytes — computed off the engines' REAL buffer nbytes
    (quantized pools pay their scale arrays here, not in a footnote)."""
    paddle.seed(7)
    m = LlamaForCausalLM(CFG).bfloat16()
    m.eval()
    engines = {q: LLMEngine(m, **_kw(kv_cache_dtype=q, block_size=16))
               for q in (None, "int8", "int4")}
    bpb = {q: e.kv_bytes_per_block() for q, e in engines.items()}
    assert bpb[None] / bpb["int8"] >= 1.9
    assert bpb[None] / bpb["int4"] >= 3.5
    # the effective-blocks gauge tells the same story off n_blocks
    # (integer blocks: the gauge floors, so the bound floors too)
    nb = engines[None].n_blocks
    assert engines[None].kv_pool_effective_blocks() == nb
    assert engines["int8"].kv_pool_effective_blocks() >= int(1.9 * nb)
    assert engines["int4"].kv_pool_effective_blocks() >= int(3.5 * nb)
    # nbytes is the real sum over payload + scale buffers
    for q, e in engines.items():
        leaves = jax.tree_util.tree_leaves([e._k, e._v])
        assert e.kv_pool_nbytes() == sum(
            int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
            for x in leaves)


def test_constructor_errors():
    m = LlamaForCausalLM(CFG)
    m.eval()
    with pytest.raises(ValueError, match="cache_impl='paged'"):
        LLMEngine(m, cache_impl="dense", kv_cache_dtype="int8")
    with pytest.raises(ValueError, match="unknown kv_cache_dtype"):
        LLMEngine(m, **_kw(kv_cache_dtype="fp8"))


# ---------------------------------------------------------------------------
# engine drift + bit-identity
# ---------------------------------------------------------------------------
# Wall-budget note (the PR-8/PR-11 conftest policy): every test below
# that builds MORE THAN the two drift engines rides the `slow` marker —
# each fused paged engine costs a fresh program compile on CPU, and
# tier-1 sits ~60 s under its 870 s cap. Tier-1 keeps the acceptance
# core: kernel parity, capacity, constructor errors, and the int8-vs-
# bf16 drift bound; the composition matrix and plumbing tests run in
# the full (slow-inclusive) suite.

@pytest.fixture(scope="module")
def bf16_toks(tiny_model, prompts):
    return _toks(LLMEngine(tiny_model, **_kw(kv_cache_dtype=None)),
                 prompts, 12)


@pytest.fixture(scope="module")
def int8_toks(tiny_model, prompts):
    return _toks(LLMEngine(tiny_model, **_kw()), prompts, 12)


class TestEngineDrift:
    def test_int8_greedy_matches_bf16_prefix(self, bf16_toks, int8_toks):
        """int8 KV quantization must not derail greedy output early: the
        stream matches the bf16 engine for at least the first 8 tokens
        on the tiny model (measured: all 12 match — the bar leaves
        rounding-luck margin, and the bench's drift metric tracks the
        production-shape number)."""
        for ref, got in zip(bf16_toks, int8_toks):
            assert _match_prefix(ref, got) >= 8

    @pytest.mark.slow
    def test_none_dtype_bit_identical(self, tiny_model, prompts,
                                      bf16_toks):
        """kv_cache_dtype=None is the pre-quantization engine: same
        tokens AND the same carried logits buffer as a plain paged
        engine (which every existing paged tier-1 suite exercises)."""
        plain = LLMEngine(tiny_model, **_kw(kv_cache_dtype=None))
        assert _toks(plain, prompts, 12) == bf16_toks
        none_eng = LLMEngine(tiny_model, **_kw(kv_cache_dtype=None))
        assert _toks(none_eng, prompts, 12) == bf16_toks
        np.testing.assert_array_equal(np.asarray(plain._logits),
                                      np.asarray(none_eng._logits))

    @pytest.mark.slow
    def test_int4_generates_and_packs(self, tiny_model, prompts):
        """int4 serving runs end to end with nibble-packed pools (half
        the payload bytes of int8); output quality is workload-dependent
        at 4 bits, so only structure is asserted here — the bench A/B
        reports its drift."""
        eng = LLMEngine(tiny_model, **_kw(kv_cache_dtype="int4"))
        outs = _toks(eng, prompts)
        assert all(len(t) == 10 for t in outs)
        payload = eng._k[0][0]
        assert payload.dtype == jnp.int8
        assert payload.shape[-1] == CFG.hidden_size \
            // CFG.num_attention_heads // 2


# ---------------------------------------------------------------------------
# the composition matrix: quantized pool x engine features
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestComposition:
    """Every engine feature x the quantized pool — `slow` as a CLASS
    per the wall-budget note above (each case compiles its own fused
    programs); the matrix is the full suite's contract, tier-1 keeps
    the kernel/capacity/drift core."""

    def test_prefix_cache_token_exact_and_reuses(self, tiny_model,
                                                 prompts):
        """Quantized pool x prefix cache: shared blocks are the same
        quantized bytes the slot would have written itself, so cache
        on/off is token-EXACT — and the second run actually hits."""
        base = _toks(LLMEngine(tiny_model, **_kw()), prompts)
        pc = LLMEngine(tiny_model, **_kw(enable_prefix_cache=True))
        assert _toks(pc, prompts) == base
        assert _toks(pc, prompts) == base      # re-run: served from cache
        assert pc.stats["prefix_hit_tokens"] > 0

    def test_stride_multi_step_exact(self, tiny_model, prompts):
        """Quantized pool x readout_stride: the compiled k-step loop runs
        the same quantized merge per iteration — bit-equal tokens."""
        base = _toks(LLMEngine(tiny_model, **_kw()), prompts)
        st = LLMEngine(tiny_model, **_kw(readout_stride=4))
        assert _toks(st, prompts) == base

    def test_legacy_scheduler_exact(self, tiny_model, prompts):
        """Quantized pool x legacy scheduler: admission prefill writes
        whole chunk-aligned blocks (one absmax scale per fresh block —
        the same bytes the fused append path produces for block-aligned
        grants), so the schedulers agree token-exactly here."""
        base = _toks(LLMEngine(tiny_model, **_kw()), prompts)
        leg = LLMEngine(tiny_model, **_kw(scheduler="legacy"))
        assert _toks(leg, prompts) == base

    def test_speculative_drift_bounded(self, tiny_model, prompts):
        """Quantized pool x verify grants: rejected drafts leave
        re-rounded block scales behind (rollback truncates tables, not
        the scale history), so spec streams are drift-BOUNDED vs the
        non-spec quantized engine, not bit-equal — the documented
        policy. Rollback itself must keep the pool invariants."""
        base = _toks(LLMEngine(tiny_model, **_kw()), prompts)
        sp = LLMEngine(tiny_model, **_kw(speculative_k=3))
        outs = _toks(sp, prompts)
        for ref, got in zip(base, outs):
            assert _match_prefix(ref, got) >= 6
        sp._check_pool_invariants()

    def test_lora_adapter_exact_vs_merged(self, prompts):
        """Quantized pool x batched multi-LoRA: the adapter delta lands
        in qkv BEFORE quantization, so the batched engine quantizes the
        same values a merged-weights engine does — token-exact."""
        from paddle_tpu.serving import (AdapterStore, apply_merged,
                                        random_lora_weights)
        store = AdapterStore(CFG, rank=4)
        store.register(random_lora_weights(CFG, rank=4, seed=3,
                                           scale=0.05), alpha=2.0)

        def fresh():
            paddle.seed(7)
            m = LlamaForCausalLM(CFG)
            m.eval()
            return m

        merged = fresh()
        apply_merged(merged, store, 1)
        ref = _toks(LLMEngine(merged, **_kw()), prompts, 6)
        eng = LLMEngine(fresh(), **_kw(adapter_store=store))
        rids = [eng.add_request(p, max_new_tokens=6, adapter_id=1)
                for p in prompts]
        while eng.has_unfinished():
            eng.step()
        outs = [eng.finished_outputs.pop(r).token_ids for r in rids]
        assert outs == ref

    def test_tp_mesh_exact(self, tiny_model, prompts, tp_mesh):
        """Quantized pool x TP mesh: scale arrays shard kv-heads with
        the pools and per-head absmax is shard-local — token-exact vs
        single-chip int8."""
        from paddle_tpu.serving.cluster import tp_engine
        base = _toks(LLMEngine(tiny_model, **_kw()), prompts)
        paddle.seed(7)
        m2 = LlamaForCausalLM(CFG)
        m2.set_state_dict(tiny_model.state_dict())
        m2.eval()
        tpe = tp_engine(m2, mesh=tp_mesh, **_kw())
        assert _toks(tpe, prompts) == base

    def test_reset_rebuilds_scales_and_stitches(self, tiny_model,
                                                prompts):
        """Quantized pool x supervised restart: reset() rebuilds the
        scale arrays with the pools (zeros over zeros = the cold state),
        pool bytes are unchanged, and a committed-token re-admission
        continues the stream with the committed prefix intact. The
        post-restart SUFFIX is drift-tolerant by policy (re-prefill
        re-quantizes whole blocks where the original run merged
        incrementally)."""
        eng = LLMEngine(tiny_model, **_kw())
        base = _toks(eng, prompts)
        nbytes = eng.kv_pool_nbytes()
        eng.reset()
        assert eng.kv_pool_nbytes() == nbytes
        for pool, scale in eng._k + eng._v:
            assert pool.dtype == jnp.int8
            assert not np.asarray(scale).any()
        committed = base[0][:4]
        rid = eng.add_request(prompts[0], max_new_tokens=10,
                              committed_tokens=committed)
        while eng.has_unfinished():
            eng.step()
        out = eng.finished_outputs.pop(rid)
        assert out.token_ids[:4] == committed
        assert len(out.token_ids) == 14


# ---------------------------------------------------------------------------
# observability plumbing + bench smoke
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_step_record_kv_fields(tiny_model, prompts):
    """StepRecords off a quantized engine carry the pool's byte size
    (payload + scales) and storage dtype; dense engines stamp None."""
    from paddle_tpu.profiler.flight_recorder import FlightRecorder
    eng = LLMEngine(tiny_model, **_kw())
    eng.flight_recorder = FlightRecorder(capacity=64)
    eng.generate(prompts[:1], max_new_tokens=3)
    recs = eng.flight_recorder.records()
    assert recs
    for r in recs:
        assert r.kv_cache_dtype == "int8"
        assert r.kv_pool_bytes == eng.kv_pool_nbytes() > 0
        d = r.to_dict()
        assert d["kv_cache_dtype"] == "int8"
    dense = LLMEngine(tiny_model, max_batch=2, max_seq_len=64,
                      chunk_size=16, scheduler="fused")
    dense.flight_recorder = FlightRecorder(capacity=64)
    dense.generate(prompts[:1], max_new_tokens=3)
    assert all(r.kv_cache_dtype is None and r.kv_pool_bytes is None
               for r in dense.flight_recorder.records())


@pytest.mark.slow
def test_kv_pool_effective_blocks_gauge(tiny_model, prompts):
    """The serve loop samples kv_pool_effective_blocks: ~2x n_blocks on
    an int8 pool, == n_blocks unquantized."""
    from paddle_tpu.serving import AsyncLLMServer
    eng = LLMEngine(tiny_model, **_kw())
    server = AsyncLLMServer(eng, max_queue_size=4)
    server.start()
    server.submit(prompts[0], max_new_tokens=3).result(timeout=60)
    snap = server.telemetry.snapshot()
    server.stop()
    eff = snap["gauges"]["kv_pool_effective_blocks"]
    assert eff >= 1.9 * eng.n_blocks


@pytest.mark.slow
def test_bench_smoke_kv_quant(monkeypatch, tmp_path):
    """CPU dry-run of the llama_serve_kv_quant bench line: equal-byte
    pool sizing gives the quantized arms more blocks, the drift metric
    rides every arm, and the artifact lands. `slow` per the wall-budget
    note above (three serve arms = three compiled engines); the tier-1
    core keeps kernel parity + capacity + drift."""
    import bench

    # moderate oversubscription: prompts of ~2 blocks in a 6-of-8-block
    # bf16 pool. (A pool barely larger than ONE prompt can ramp-thrash
    # the fused scheduler — a pre-existing corner, not a quantization
    # one; the bench arm's wall deadline turns it into a loud failure.)
    for k, v in {"BENCH_BATCH": "2", "BENCH_REQUESTS": "3",
                 "BENCH_NEW_TOKENS": "4", "BENCH_LAYERS": "1",
                 "BENCH_HIDDEN": "64", "BENCH_FF": "128",
                 "BENCH_CHUNK": "16", "BENCH_BLOCK": "8",
                 "BENCH_PROMPT": "16", "BENCH_POOL_FRAC": "0.75",
                 "BENCH_ARTIFACT_DIR": str(tmp_path)}.items():
        monkeypatch.setenv(k, v)
    out = bench._bench_other("llama_serve_kv_quant")
    assert out["metric"] == "llama_serve_kv_quant_tokens_per_sec"
    assert out["value"] > 0
    # equal-byte sizing caps at the full (never-preempts) demand
    full = out["full_blocks"]
    bf16_blocks = out["bf16"]["pool_blocks"]
    assert out["int8"]["pool_blocks"] >= min(full, int(1.9 * bf16_blocks))
    assert out["int4"]["pool_blocks"] >= min(full, int(3.5 * bf16_blocks))
    assert out["int8"]["pool_bytes"] <= out["bf16"]["pool_bytes"]
    assert out["int4"]["pool_bytes"] <= out["bf16"]["pool_bytes"]
    for arm in ("int8", "int4"):
        d = out[arm]["drift_vs_bf16"]
        assert 0 <= d["min_match_prefix"] <= 4
        assert "first_divergence_step" in d
    assert (tmp_path / "llama_serve_kv_quant.json").exists()
