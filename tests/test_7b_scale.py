"""North-star scale proof: the REAL Llama-2-7B compiles and fits v5e HBM.

VERDICT r2 #1: nothing had ever compiled the actual 32-layer model — the
bench proxies with 3 layers. Without a pod, the scale proof is AOT: build the
full 7B ABSTRACTLY (LazyGuard — zero host memory), assign the hybrid
placements, compile the complete fused train step (fwd+bwd+AdamW, remat) on
the virtual 8-device mesh, and read the per-device budget out of the
compiled program.

The budget decomposes into two honestly-measurable parts:

1. **State** (params + AdamW master/moments + batch): exact per-device bytes
   from the compiled SPMD executable's ``argument_size_in_bytes`` (outputs
   alias into the donated inputs). This is the dominant, static residency.
2. **Backward residuals** (what the autodiff actually saves between forward
   and backward): ``jax._src.ad_checkpoint.saved_residuals`` on the very
   loss the step differentiates — trace-level truth, backend-independent.
   This is asserted UNSHARDED (conservative: layer boundaries are replicated
   under pure TP). The XLA *CPU* backend's ``temp_size_in_bytes`` is NOT
   used for the fit claim: measured here (and with a pure-jax repro), CPU
   buffer assignment reports identical temps with and without
   ``jax.checkpoint``, so it cannot see the remat structure that governs TPU
   residency. In-segment transients on the TPU path are MEASURED, not
   assumed (round 4, bench.py BENCH_MODEL=memcheck on the real chip): at
   the single-chip bench config (879M, B=6, S=2048, ff=11264 unsharded)
   the TPU compiler's peak exceeds state+residuals by 1.068 GB (9.25% of
   peak — the residual model accounts for the rest of the compiler's temp
   bytes exactly). Transients scale with the largest live activation block
   (B, S, ff/mp); at the TP=8 proof config (B=4, ff=11008/8) that block is
   ~12x smaller → ~90 MB, inside the 0.88 GB headroom left after 1.+2.

Reference analog: test/auto_parallel/hybrid_strategy/semi_auto_llama.py:1
(the hybrid-parallel llama train config this mirrors), with the memory proof
standing in for a pod run.

Configs proven (BASELINE.json north star + config 3):
- TP=8 with AdamW state sharded over mp (ZeRO-1-over-mp; without it, 7B
  state alone exceeds HBM).
- TP=4 x ZeRO-2 (sharding=2): state+grad-accumulation over mp x sharding,
  grad reduction present in the compiled HLO.

Budget: v5e usable HBM = 15.75 GB/chip (measured).
"""
import json

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt_mod
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import fleet_state
from paddle_tpu.jit.api import TrainStep
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.utils.hlo_check import CompileReport

import pytest

# Importable again since the jax<0.5 shard_map import fallback (round
# 6) un-broke collection; the file is gated behind the `slow` marker
# because tier-1 has a hard wall-time budget and at the seed this file
# contributed a collection ERROR (zero runtime). Run explicitly or
# without -m "not slow" for full coverage.
pytestmark = pytest.mark.slow


V5E_HBM = 15.75e9
N_DEV = 8
B, S = 4, 2048

# THE canonical Megatron TP placement plan lives with the model
# (paddle_tpu.models.llama.LLAMA_TP_RULES); the pod worker and the
# sharded-generate test consume the same table.
from paddle_tpu.models.llama import llama_tp_spec as _tp_spec  # noqa: E402


def _fleet_init(dp, mp, sharding, stage=None):
    fleet_state.set_hcg(None)
    fleet_state.set_strategy(None)
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": 1, "sharding_degree": sharding,
                               "sep_degree": 1}
    if stage is not None:
        strategy.sharding_configs = {"stage": stage}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group()


def _build_7b(mesh, batch_spec):
    """Abstract 7B + TP placements + AdamW; returns (model, opt, batch)."""
    from paddle_tpu.core.flags import set_flags
    # the Pallas fused update would trace in interpret mode on this CPU
    # backend (grid unrolled into the graph at 7B scale); the XLA update has
    # the identical memory/placement contract, which is what's proven here
    set_flags({"use_fused_adamw": False})
    cfg = LlamaConfig.llama2_7b(use_recompute=True,
                                max_position_embeddings=S)
    paddle.seed(0)
    with paddle.LazyGuard():
        model = LlamaForCausalLM(cfg).bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    assert n_params > 6.7e9, f"not the real 7B: {n_params}"
    for name, p in model.named_parameters():
        p._value = jax.ShapeDtypeStruct(
            p._value.shape, p._value.dtype,
            sharding=NamedSharding(mesh, _tp_spec(name)))
    optimizer = opt_mod.AdamW(learning_rate=3e-4,
                              parameters=model.parameters(),
                              weight_decay=0.01, multi_precision=True)
    from paddle_tpu.core.tensor import Tensor
    ids = Tensor(jax.ShapeDtypeStruct((B, S), jnp.int32,
                                      sharding=NamedSharding(mesh,
                                                             batch_spec)))
    labels = Tensor(jax.ShapeDtypeStruct((B, S), jnp.int32,
                                         sharding=NamedSharding(mesh,
                                                                batch_spec)))
    return model, optimizer, (ids, labels)


def _loss_fn(m, ids, labels):
    loss, _ = m(ids, labels=labels)
    return loss


def _residual_bytes(step, batch, dp_shards=1):
    """Backward-residual bytes via the shared memory model
    (paddle_tpu/utils/memory_model.py — the single import site of jax's
    private saved_residuals), with a loud skip when a jax upgrade moves
    the private API."""
    import pytest
    from paddle_tpu.utils.memory_model import residual_bytes
    try:
        return residual_bytes(step, batch, dp_shards=dp_shards, seq_len=S)
    except RuntimeError as e:
        if "saved_residuals" in str(e):
            pytest.skip(str(e))
        raise


def _report(compiled):
    return CompileReport(compiled.as_text(), compiled.memory_analysis(),
                         (), ())


def _check_fit(tag, step, batch, dp_shards=1):
    compiled = step.aot_compile(*batch)
    rep = _report(compiled)
    state_per_dev = int(rep.stats.argument_size_in_bytes)
    residuals = _residual_bytes(step, batch, dp_shards=dp_shards)
    line = {"event": "7b_scale_proof", "config": tag,
            "state_bytes_per_dev": state_per_dev,
            "residual_bytes_conservative": residuals,
            "out_bytes_per_dev": rep.out_bytes,
            "cpu_backend_temp_bytes_unreliable": rep.temp_bytes,
            "fit_budget_bytes": int(V5E_HBM)}
    print(json.dumps(line))

    resident = state_per_dev + residuals
    assert resident <= V5E_HBM, \
        f"7B {tag} does not fit v5e: state {state_per_dev/1e9:.2f} + " \
        f"residuals {residuals/1e9:.2f} GB"
    # sanity floor: a silently replicated model would blow the budget; a
    # degenerate compile would fall far below any real 1/8 shard of ~94 GB
    assert state_per_dev >= 8e9, \
        f"suspiciously small state: {state_per_dev/1e9:.2f} GB"
    # outputs (updated params + slots) stay sharded — no full re-gather
    assert rep.out_bytes <= state_per_dev + 1e9
    return rep


def test_7b_tp8_compiles_and_fits():
    """North star: TP=8 hybrid step on the real 32-layer 7B within the
    15.75 GB v5e budget."""
    hcg = _fleet_init(dp=1, mp=N_DEV, sharding=1)
    mesh = hcg.mesh.jax_mesh()
    model, optimizer, batch = _build_7b(mesh, batch_spec=P())
    # AdamW state (master+moments, ~81 GB) sharded 8-way over the mp axis —
    # without this the state alone exceeds HBM
    wrapped = fleet.DygraphShardingOptimizer(optimizer, hcg, axis="mp",
                                             stage=1)
    assert wrapped._stage == 1
    step = TrainStep(model, _loss_fn, optimizer, donate=True)
    rep = _check_fit("tp8_zero1state", step, batch)

    # TP contract: row-parallel projections + vocab-parallel embedding and
    # CE reductions land as all-reduce (fwd + bwd); 32 layers give >= 64
    counts = rep.collective_counts()
    assert counts["all-reduce"] + counts["reduce-scatter"] >= 64, counts


def test_7b_tp4_zero2_compiles_and_fits():
    """BASELINE config 3 composition: TP=4 x ZeRO-2 (sharding=2), grads
    reduced into 1/N state shards inside the compiled step."""
    hcg = _fleet_init(dp=1, mp=4, sharding=2, stage=2)
    mesh = hcg.mesh.jax_mesh()
    model, optimizer, batch = _build_7b(mesh,
                                        batch_spec=P("sharding", None))
    model, optimizer, _ = dist.group_sharded_parallel(model, optimizer,
                                                      "os_g")
    step = TrainStep(model, _loss_fn, optimizer, donate=True)
    rep = _check_fit("tp4_zero2", step, batch, dp_shards=2)

    counts = rep.collective_counts()
    # the sharding-axis grad reduction must be present; on this backend it
    # can legally compile as reduce-scatter or all-reduce(+slice)
    assert counts["reduce-scatter"] + counts["all-reduce"] >= 64, counts


def test_7b_state_bytes_budget_math():
    """The sharded-state arithmetic itself (no compile): bf16 params + fp32
    master + fp32 moments for 6.74B params = ~94 GB; any 8-way factored
    placement must land ~11.8 GB/device — the headroom the compiled proofs
    above consume with batch + residuals."""
    n = 6_738_000_000
    per_param = 2 + 4 + 4 + 4
    total = n * per_param
    assert total / N_DEV < V5E_HBM * 0.80, \
        "state alone leaves no activation headroom — plan invalid"


def test_lazyguard_abstract_then_materialize():
    """LazyGuard builds abstract (zero-memory) models; materialize() runs
    the recorded initializers, honoring dtype rewrites applied while
    abstract. Reference: paddle.LazyGuard deferred init."""
    fleet_state.set_hcg(None)
    fleet_state.set_strategy(None)
    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    with paddle.LazyGuard():
        model = LlamaForCausalLM(cfg).bfloat16()
    for p in model.parameters():
        assert isinstance(p._value, jax.ShapeDtypeStruct)
        assert p._value.dtype == jnp.bfloat16
    model.materialize()
    for p in model.parameters():
        assert isinstance(p._value, jax.Array)
        assert p._value.dtype == jnp.bfloat16
    # materialized weights are real draws and the model runs
    w = np.asarray(model.parameters()[0]._value, dtype=np.float32)
    assert np.abs(w).sum() > 0
    out = model(paddle.to_tensor(np.array([[1, 2, 3]], np.int32)))
    assert tuple(out.shape) == (1, 3, cfg.vocab_size)


def test_7b_tp8_accumulation_compiles_and_fits():
    """The flagship bench config at full scale: TP=8, ZeRO-1 state sharding,
    bf16 moments, gradient accumulation. aot_compile returns the
    (microstep, update) program pair; BOTH must fit — the microstep carries
    the persistent fp32 accumulators (which inherit the param's TP sharding:
    replicated they alone would be 27 GB/device), the update carries the
    optimizer state."""
    from paddle_tpu.core.flags import set_flags
    hcg = _fleet_init(dp=1, mp=N_DEV, sharding=1)
    mesh = hcg.mesh.jax_mesh()
    set_flags({"adamw_bf16_moments": True})
    try:
        model, optimizer, batch = _build_7b(mesh, batch_spec=P())
        wrapped = fleet.DygraphShardingOptimizer(optimizer, hcg, axis="mp",
                                                 stage=1)
        assert wrapped._stage == 1
        step = TrainStep(model, _loss_fn, optimizer, donate=True,
                         accumulate_steps=2)
        grad_c, upd_c = step.aot_compile(*batch)
        g_args = int(grad_c.memory_analysis().argument_size_in_bytes)
        u_args = int(upd_c.memory_analysis().argument_size_in_bytes)
        residuals = _residual_bytes(step, batch)
        print(json.dumps({"event": "7b_scale_proof",
                          "config": "tp8_accum2_bf16moments",
                          "microstep_args_per_dev": g_args,
                          "update_args_per_dev": u_args,
                          "residual_bytes_conservative": residuals}))
        assert g_args + residuals <= V5E_HBM, \
            f"microstep does not fit: {(g_args + residuals)/1e9:.2f} GB"
        assert u_args <= V5E_HBM, f"update does not fit: {u_args/1e9:.2f} GB"
        # accumulators must NOT be replicated: microstep args = params(1/8)
        # + accs + batch + rope. Replicated accs alone would be ~27 GB.
        assert g_args <= 8e9, \
            f"accumulators replicated? microstep args {g_args/1e9:.2f} GB"
    finally:
        set_flags({"adamw_bf16_moments": False})


def _run_pod_worker(ndev, config, timeout=2400):
    """Spawn tests/workers/pod_proof_worker.py with its own XLA device-count
    flags (the suite's backend is pinned to 8 devices) and parse its JSON."""
    import os
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(__file__), "workers",
                          "pod_proof_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    proc = subprocess.run([sys.executable, script, str(ndev), config],
                          capture_output=True, text=True, timeout=timeout,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def test_7b_pod_topology_256():
    """VERDICT r3 #1: the NORTH-STAR mesh itself — dp=32 x tp=8 on 256
    virtual devices. The 7B step must compile with per-device state matching
    the one-host TP=8 proof (dp replicates state), and the compiled HLO must
    carry BOTH the TP reduction (groups of 8) and the dp-axis grad
    all-reduce (groups of 32). A ZeRO-1-over-dp variant must shrink the
    optimizer state a further 32x (proven at dp=2 scale by the
    dp2_tp8_zero1dp config below — the plan shards master+moments over
    dp x mp). Reference analog:
    test/auto_parallel/hybrid_strategy/semi_auto_llama.py:1 at its target
    topology."""
    out = _run_pod_worker(256, "dp_tp")
    print(json.dumps(out))
    state = out["state_bytes_per_dev"]
    # must match the TP=8 proof (test_7b_tp8_compiles_and_fits: ~11.79 GB)
    assert 11.5e9 <= state <= 12.1e9, state
    assert state <= V5E_HBM
    groups = set(out["reduction_group_sizes"])
    assert 8 in groups, f"TP reduction groups missing: {groups}"
    assert 32 in groups, f"dp-axis grad all-reduce missing: {groups}"
    counts = out["collective_counts"]
    assert counts["all-reduce"] + counts["reduce-scatter"] >= 64, counts


def test_7b_zero1_over_dp_shrinks_state():
    """ZeRO-1-over-dp on TOP of the TP=8 state sharding: master+moments
    stored sharded over (dp x mp), so the optimizer-state component drops by
    the dp degree. Verified at dp=2 (16 devices — the composition is
    degree-agnostic; the same config at dp=32 x tp=8 measured
    2,002,134,536 B/device on 256 virtual devices, recorded in
    PROGRESS.jsonl pod_topology_proof): 11.79 GB -> ~6.74 GB/device =
    params(1/8) + opt state(1/16) exactly."""
    out = _run_pod_worker(16, "dp_tp_zero1dp")
    print(json.dumps(out))
    state = out["state_bytes_per_dev"]
    # params bf16/8 (1.68) + AdamW fp32 master+moments/16 (5.05) + batch
    assert 6.4e9 <= state <= 7.1e9, state
    groups = set(out["reduction_group_sizes"])
    assert 8 in groups and 2 in groups, groups


def test_7b_pp_tp_scheduled_pipeline():
    """7B through the SCHEDULED pipeline runtime (1F1B) composed with TP
    inside each stage: pp=2 x tp=4 on 8 devices (the same runtime compiles
    pp=8 x tp=8 x dp=4 at 256 — exercised by the pod worker's pp_tp config;
    kept at 8 here for CI time). Asserts the ring collective-permutes and TP
    all-reduces coexist in one compiled program and per-device state shards
    over BOTH axes."""
    out = _run_pod_worker(8, "pp_tp")
    print(json.dumps(out))
    state = out["state_bytes_per_dev"]
    # body 6.21B params: bf16 + fp32 master + fp32 moments = 14 B/param over
    # pp*tp=8 -> ~10.9 GB; embed/head replicated over pp, sharded over tp
    assert state <= V5E_HBM, state
    assert state >= 8e9, f"suspiciously small: {state}"
    counts = out["collective_counts"]
    assert counts["collective-permute"] >= 2, counts   # fwd + bwd rings
    assert counts["all-reduce"] >= 8, counts           # TP inside stages


def test_7b_pp_tp_dp_256_pod():
    """VERDICT r4 weak #8: the pp8 x tp8 x dp4 composition AT 256 virtual
    devices, asserted (previously only recorded in PROGRESS). The 7B
    compiles through the scheduled 1F1B runtime with per-device state a
    ~6.3x shrink vs the TP=8-only plan (11.79 GB -> ~1.88 GB: body params
    shard over pp x tp, embed/head replicate over pp), and ONE compiled
    program carries the stage ring (collective-permute), the in-stage TP
    all-reduces (groups of 8) and the dp grad reduction (groups of 4).
    ~65 s compile on CPU. Reference:
    test/auto_parallel/hybrid_strategy/semi_auto_llama.py:1."""
    out = _run_pod_worker(256, "pp_tp")
    print(json.dumps(out))
    state = out["state_bytes_per_dev"]
    assert 1.6e9 <= state <= 2.2e9, state
    counts = out["collective_counts"]
    assert counts["collective-permute"] >= 2, counts   # fwd + bwd rings
    assert counts["all-reduce"] >= 8, counts           # TP + dp reductions
    groups = set(out["reduction_group_sizes"])
    assert 8 in groups, f"TP groups missing: {groups}"
    assert 4 in groups, f"dp groups missing: {groups}"


def test_7b_tp8_stochastic_rounding_state_footprint():
    """Master-weight-free AdamW (adamw_stochastic_rounding + bf16 moments)
    at the real 7B: per-device state drops from ~11.8 GB (bf16 p + fp32
    master + fp32 m/v = 14 B/param) to ~5 GB (bf16 p/m/v = 6 B/param) —
    the extra HBM headroom is what buys bigger per-device batches. On-chip
    throughput measured equal to the master-weight chain; trajectories are
    flag-gated (not reference-exact)."""
    from paddle_tpu.core.flags import set_flags
    hcg = _fleet_init(dp=1, mp=N_DEV, sharding=1)
    mesh = hcg.mesh.jax_mesh()
    set_flags({"adamw_stochastic_rounding": True,
               "adamw_bf16_moments": True})
    try:
        cfg = LlamaConfig.llama2_7b(use_recompute=True,
                                    max_position_embeddings=S)
        paddle.seed(0)
        with paddle.LazyGuard():
            model = LlamaForCausalLM(cfg).bfloat16()
        for name, p in model.named_parameters():
            p._value = jax.ShapeDtypeStruct(
                p._value.shape, p._value.dtype,
                sharding=NamedSharding(mesh, _tp_spec(name)))
        optimizer = opt_mod.AdamW(learning_rate=3e-4,
                                  parameters=model.parameters(),
                                  weight_decay=0.01, multi_precision=False)
        wrapped = fleet.DygraphShardingOptimizer(optimizer, hcg, axis="mp",
                                                 stage=1)
        assert wrapped._stage == 1
        from paddle_tpu.core.tensor import Tensor
        ids = Tensor(jax.ShapeDtypeStruct((B, S), jnp.int32,
                                          sharding=NamedSharding(mesh, P())))
        step = TrainStep(model, _loss_fn, optimizer, donate=True)
        compiled = step.aot_compile(ids, ids)
        state = int(compiled.memory_analysis().argument_size_in_bytes)
        residuals = _residual_bytes(step, (ids, ids))
        print(json.dumps({"event": "7b_scale_proof", "config": "tp8_sr",
                          "state_bytes_per_dev": state,
                          "residual_bytes_conservative": residuals}))
        # 6 B/param of state -> ~5 GB/device at TP=8 (vs 11.8 with masters)
        assert state <= 6.2e9, f"SR state too big: {state/1e9:.2f} GB"
        assert state + residuals <= V5E_HBM * 0.6, "headroom claim violated"
    finally:
        set_flags({"adamw_stochastic_rounding": False,
                   "adamw_bf16_moments": False})
