"""distributed.rpc tests — in-process pair and subprocess workers.

Reference strategy: rpc tests spin up local workers with fabricated env
(test/rpc/ in the reference)."""
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from paddle_tpu.core import native
from paddle_tpu.distributed import rpc

# Importable again since the jax<0.5 shard_map import fallback (round
# 6) un-broke collection; the file is gated behind the `slow` marker
# because tier-1 has a hard wall-time budget and at the seed this file
# contributed a collection ERROR (zero runtime). Run explicitly or
# without -m "not slow" for full coverage.
pytestmark = pytest.mark.slow


def _add(a, b):
    return a + b


def _boom():
    raise ValueError("remote failure")


class TestRpcSingleWorker:
    def setup_method(self):
        rpc.init_rpc("solo", rank=0, world_size=1,
                     master_endpoint="127.0.0.1:0")

    def teardown_method(self):
        rpc.shutdown()

    def test_self_call_sync(self):
        assert rpc.rpc_sync("solo", _add, args=(2, 3)) == 5

    def test_self_call_async(self):
        fut = rpc.rpc_async("solo", _add, args=(np.ones(3), np.ones(3)))
        np.testing.assert_allclose(fut.wait(), 2 * np.ones(3))

    def test_remote_exception_propagates(self):
        with pytest.raises(ValueError, match="remote failure"):
            rpc.rpc_sync("solo", _boom)

    def test_worker_info(self):
        info = rpc.get_worker_info("solo")
        assert info.rank == 0 and info.port > 0
        infos = rpc.get_all_worker_infos()
        assert [i.name for i in infos] == ["solo"]


_CHILD = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import sys
from paddle_tpu.distributed import rpc
from tests.test_rpc import _add

rpc.init_rpc("worker1", rank=1, world_size=2, master_endpoint=sys.argv[1])
# worker1 calls back into worker0 then serves until shutdown
result = rpc.rpc_sync("worker0", _add, args=(10, 20))
assert result == 30, result
rpc.shutdown()
print("child ok", flush=True)
"""


@pytest.mark.skipif(not native.available(), reason="native runtime unavailable")
def test_two_process_rpc(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "child.py"
    script.write_text(_CHILD)

    # init worker0 in a thread since init_rpc barriers on both workers
    results = {}

    def worker0():
        rpc.init_rpc("worker0", rank=0, world_size=2,
                     master_endpoint=f"127.0.0.1:{port}")
        results["init"] = True
        # serve until the child has called us and shut down
        rpc.shutdown()

    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    t = threading.Thread(target=worker0)
    t.start()
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, str(script), f"127.0.0.1:{port}"],
        cwd=repo_root, env=env, capture_output=True, text=True, timeout=300)
    t.join(timeout=60)
    assert out.returncode == 0, out.stderr
    assert "child ok" in out.stdout
    assert results.get("init")
