"""Tensor method parity: the reference's full tensor_method_func surface
(394 names) must exist on Tensor, and bound methods must equal the top-level
functions."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def t2n(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def test_all_reference_methods_exist():
    # spot-list drawn from the reference tensor_method_func groups
    sample = ["qr", "lu", "lu_unpack", "svd_lowrank", "cov", "corrcoef",
              "histogram", "kron", "outer", "inner", "diff", "trapezoid",
              "frexp", "ldexp", "vander", "polar", "take", "sgn", "view",
              "view_as", "unflatten", "pinv", "multi_dot", "solve",
              "cholesky_solve", "tensordot", "diag_embed", "diagflat",
              "multinomial", "renorm", "isin", "isneginf", "isposinf",
              "isreal", "signbit", "copysign", "i0", "i1", "polygamma",
              "gcd", "lcm", "atleast_1d", "atleast_2d", "slice_scatter",
              "select_scatter", "index_put", "index_fill", "masked_scatter",
              "combinations", "cdist", "nanquantile", "is_complex",
              "is_floating_point", "rank", "real", "imag", "stft", "istft",
              "set_", "resize_", "top_p_sampling", "cauchy_", "geometric_",
              "bernoulli_", "exponential_", "log_normal_",
              "asin_", "cumsum_", "logical_and_", "bitwise_and_",
              "erfinv_", "atanh_", "cosh_", "acosh_", "asinh_"]
    missing = [n for n in sample if not hasattr(Tensor, n)]
    assert not missing, missing


def test_method_equals_function(rng):
    x = paddle.to_tensor(rng.standard_normal((3, 4)).astype(np.float32))
    np.testing.assert_allclose(t2n(x.outer(x.flatten())),
                               t2n(paddle.outer(x, x.flatten())))
    np.testing.assert_allclose(t2n(x.kron(x)), t2n(paddle.kron(x, x)))
    q, r = x.qr()
    np.testing.assert_allclose(t2n(q) @ t2n(r), t2n(x), atol=1e-5)


def test_inplace_methods_write_back(rng):
    x = paddle.to_tensor(np.array([0.5, -0.2], np.float32))
    y = x.atanh_()
    assert y is x
    np.testing.assert_allclose(t2n(x), np.arctanh([0.5, -0.2]), rtol=1e-6)
    z = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
    z.erfinv_()  # erfinv(1)=inf — just check write-back happened on finite
    x2 = paddle.to_tensor(np.array([0.3, 0.6], np.float32))
    before = t2n(x2).copy()
    x2.cosh_()
    np.testing.assert_allclose(t2n(x2), np.cosh(before), rtol=1e-6)


def test_set_and_resize():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32))
    src = paddle.to_tensor(np.ones((2, 3), np.float32))
    x.set_(src)
    assert tuple(x.shape) == (2, 3)
    with pytest.raises(ValueError, match="fill_zero"):
        x.resize_([8])  # growing without fill_zero=True is an error
    x.resize_([8], fill_zero=True)
    assert tuple(x.shape) == (8,)
    np.testing.assert_allclose(t2n(x)[:6], 1.0)
    np.testing.assert_allclose(t2n(x)[6:], 0.0)
    x.resize_([2, 2])
    assert tuple(x.shape) == (2, 2)


def test_top_p_sampling(rng):
    probs = np.array([[0.5, 0.3, 0.1, 0.1],
                      [0.05, 0.05, 0.05, 0.85]], np.float32)
    ps = np.array([[0.6], [0.5]], np.float32)
    vals, ids = paddle.top_p_sampling(paddle.to_tensor(probs),
                                      paddle.to_tensor(ps), seed=7)
    iv = t2n(ids).ravel()
    # row 0: nucleus = {0, 1}; row 1: nucleus = {3}
    assert iv[0] in (0, 1) and iv[1] == 3
    np.testing.assert_allclose(t2n(vals).ravel(),
                               probs[np.arange(2), iv], rtol=1e-6)


def test_create_tensor():
    t = paddle.create_tensor("float32", name="buf")
    assert t.shape == [0] and t.name == "buf"


def test_stft_method(rng):
    x = paddle.to_tensor(rng.standard_normal((1, 512)).astype(np.float32))
    spec = x.stft(n_fft=64, hop_length=16)
    assert t2n(spec).shape[0] == 1 and np.iscomplexobj(t2n(spec))


def test_pipeline_schedule_modes():
    # schedule_mode maps onto the SPMD pipeline's remat/interleave policy
    import paddle_tpu.distributed.fleet as fleet
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.pp_layers import PipelineLayer, LayerDesc
    from paddle_tpu.distributed.fleet.pipeline_parallel import PipelineParallel

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 2,
                               "sharding_degree": 1, "sep_degree": 1,
                               "pp_configs": {}}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()

    def make(mode=None):
        descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(4)]
        layers = PipelineLayer(descs, num_stages=2,
                               loss_fn=lambda o, y: ((o - y) ** 2).mean())
        st = fleet.DistributedStrategy()
        st.hybrid_configs = strategy.hybrid_configs
        if mode is not None:
            st.pipeline_configs["schedule_mode"] = mode
        return PipelineParallel(layers, hcg, st)

    # default = FThenB semantics (whole-scan autodiff, model remat config
    # untouched); explicit 1F1B/ZBH1 select the scheduled_pipeline runtimes
    assert make()._schedule_mode == "FTHENB"
    pp_f = make("FThenB")
    assert pp_f._schedule_mode == "FTHENB" and pp_f._remat is False
    assert make("1F1B")._schedule_mode == "1F1B"
    assert make("ZBH1")._schedule_mode == "ZBH1"
    with pytest.raises(ValueError, match="schedule_mode"):
        make("bogus")
    with pytest.raises(ValueError, match="VPP"):
        make("VPP")
