"""Multichip serving (paddle_tpu/serving/cluster.py) — TP engine parity,
TP kernel shard_map parity, and the ReplicaRouter's placement / failover
/ drain contracts. All tier-1 tests run on the conftest `tp_mesh` (4
virtual CPU devices, tiny shapes); the 8-device big-mesh variant is
gated ``slow``.

The acceptance bars from the ISSUE:

* TP engine (tp=4, CPU) is TOKEN-EXACT greedy-parity with the
  single-chip engine for dense AND paged cache impls, prefix cache on
  and off (``test_tp_engine_greedy_parity``).
* Router failover converts a dead replica's queued requests into
  resubmission (identical tokens on a survivor), in-flight ones into
  ``finish_reason="replica_lost"``, and the survivors' pool invariants
  hold (``test_router_failover_mid_stream``; PADDLE_TPU_POOL_CHECKS is
  armed suite-wide by conftest).
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (AsyncLLMServer, FaultInjector,
                                ReplicaRouter, RestartPolicy)
from paddle_tpu.serving.cluster import shard_model_tp, tp_engine

V = 96


def _build_model():
    paddle.seed(7)
    cfg = LlamaConfig(vocab_size=V, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def ref_model():
    return _build_model()


@pytest.fixture(scope="module")
def tp_model(tp_mesh):
    """Same weights as ref_model (same seed), laid out TP-sharded."""
    return shard_model_tp(_build_model(), tp_mesh)


def _prompts(seed, sizes):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, V, size=(n,)).astype(np.int32) for n in sizes]


ENGINE_CONFIGS = {
    "dense_legacy": dict(),
    "dense_fused": dict(scheduler="fused"),
    "paged": dict(cache_impl="paged", block_size=8, scheduler="fused"),
    "paged_prefix": dict(cache_impl="paged", block_size=8,
                         scheduler="fused", enable_prefix_cache=True),
}

# the ISSUE's tier-1 acceptance matrix is dense AND paged, prefix cache
# on and off — dense×fused adds a 4th engine-compile pair for a scheduler
# the paged configs already exercise at TP, so it rides the slow lane
# (tier-1 wall budget)
_CONFIG_PARAMS = [
    # tier-1 wall budget: dense_fused (PR 6) and the plain paged cell
    # (PR 14 — subsumed by paged_prefix, the richer composition) ride
    # the slow lane
    pytest.param(name, marks=[pytest.mark.slow]
                 if name in ("dense_fused", "paged") else [])
    for name in ENGINE_CONFIGS
]


# ---------------------------------------------------------------------------
# Level 1 — the TP engine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("config", _CONFIG_PARAMS)
def test_tp_engine_greedy_parity(tp_mesh, tp_model, ref_model, config):
    """tp=4 virtual devices, CPU: token-exact greedy parity vs the
    single-chip engine — dense and paged, prefix cache off and on. The
    KV buffers must be REALLY sharded (not replicated) for the test to
    mean anything."""
    kw = dict(ENGINE_CONFIGS[config])
    prompts = _prompts(3, (9, 5, 17))
    ref = LLMEngine(ref_model, max_batch=2, max_seq_len=64, chunk_size=16,
                    **kw)
    want = [o.token_ids for o in ref.generate(prompts, max_new_tokens=8)]

    eng = LLMEngine(tp_model, max_batch=2, max_seq_len=64, chunk_size=16,
                    mesh=tp_mesh, **kw)
    assert eng.tp_degree() == 4
    # the pools genuinely shard on the kv-head dim: each shard holds
    # kvh / 4 heads
    spec = eng._k[0].sharding.spec
    head_dim = 1 if kw.get("cache_impl") == "paged" else 2
    assert spec[head_dim] == "tp", spec
    shard_shape = next(iter(eng._k[0].addressable_shards)).data.shape
    assert shard_shape[head_dim] == eng._k[0].shape[head_dim] // 4
    got = [o.token_ids for o in eng.generate(prompts, max_new_tokens=8)]
    assert got == want


@pytest.mark.slow   # tier-1 wall budget (PR 14): TP parity stays
# tier-1 via the engine-level matrix above, and TP-through-server is
# exercised by __graft_entry__ dryrun's serve=engine_tp leg
def test_tp_engine_serves_through_async_server(tp_mesh, tp_model,
                                               ref_model):
    """The TP paged engine behind AsyncLLMServer streams the identical
    tokens the single-chip engine generates (prefill + fused mixed steps
    + the pipelined serve loop, all with sharded pools)."""
    prompts = _prompts(11, (21, 6))
    ref = LLMEngine(ref_model, max_batch=2, max_seq_len=64, chunk_size=16,
                    cache_impl="paged", block_size=8, scheduler="fused")
    want = [o.token_ids for o in ref.generate(prompts, max_new_tokens=6)]

    eng = LLMEngine(tp_model, max_batch=2, max_seq_len=64, chunk_size=16,
                    cache_impl="paged", block_size=8, scheduler="fused",
                    mesh=tp_mesh)
    server = AsyncLLMServer(eng, max_queue_size=4)
    server.start()
    try:
        handles = [server.submit(p, max_new_tokens=6) for p in prompts]
        results = [h.result(timeout=300) for h in handles]
    finally:
        server.stop()
    assert [r.token_ids for r in results] == want


def test_tp_engine_rejects_indivisible_kv_heads(tp_mesh):
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=V, hidden_size=64, intermediate_size=96,
                      num_hidden_layers=1, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=64)
    m = LlamaForCausalLM(cfg)
    m.eval()
    with pytest.raises(ValueError, match="num_key_value_heads"):
        LLMEngine(m, max_batch=1, max_seq_len=32, mesh=tp_mesh)


# ---------------------------------------------------------------------------
# TP kernels — shard_map'd Pallas decode/append (interpret mode)
# ---------------------------------------------------------------------------

def _kernel_inputs(rng, B=2, Hq=8, Hkv=4, D=16, BS=8, MB=4, NB=9):
    q = rng.standard_normal((B, Hq, D)).astype(np.float32)
    kp = rng.standard_normal((NB, Hkv, BS, D)).astype(np.float32)
    vp = rng.standard_normal((NB, Hkv, BS, D)).astype(np.float32)
    tables = np.array([[0, 1, 2, -1], [3, 4, -1, -1]], np.int32)
    lens = np.array([19, 10], np.int32)
    return q, kp, vp, tables, lens


def test_tp_kernel_decode_parity(tp_mesh, rng):
    """The shard_map'd decode kernel (kv-heads over "tp") matches the
    unsharded kernel bit-for-bit in interpret mode — fused new-token
    write included (per-shard pools round-trip through the aliased
    outputs)."""
    from paddle_tpu.ops.kernels.paged_attention import (
        paged_attention_decode, paged_attention_decode_tp)
    q, kp, vp, tables, lens = _kernel_inputs(rng)
    nk = rng.standard_normal((2, 4, 16)).astype(np.float32)
    nv = rng.standard_normal((2, 4, 16)).astype(np.float32)
    ref = paged_attention_decode(q, kp.copy(), vp.copy(), tables, lens,
                                 new_k=nk, new_v=nv)
    got = paged_attention_decode_tp(q, kp.copy(), vp.copy(), tables, lens,
                                    tp_mesh, new_k=nk, new_v=nv)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(r), np.asarray(g),
                                   rtol=1e-5, atol=1e-5)
    # read-only form too (no fused write)
    ref_o = paged_attention_decode(q, kp, vp, tables, lens)
    got_o = paged_attention_decode_tp(q, kp, vp, tables, lens, tp_mesh)
    np.testing.assert_allclose(np.asarray(ref_o), np.asarray(got_o),
                               rtol=1e-5, atol=1e-5)


def test_tp_kernel_append_parity(tp_mesh, rng):
    """Append (mixed prefill+decode) kernel under shard_map: q_lens
    mixing full chunks, partial chunks and an idle (0) slot."""
    from paddle_tpu.ops.kernels.paged_attention import (
        paged_attention_append, paged_attention_append_tp)
    q, kp, vp, tables, lens = _kernel_inputs(rng)
    S = 4
    qa = rng.standard_normal((2, S, 8, 16)).astype(np.float32)
    nk = rng.standard_normal((2, S, 4, 16)).astype(np.float32)
    nv = rng.standard_normal((2, S, 4, 16)).astype(np.float32)
    for qlens in ([4, 2], [1, 0]):
        qlens = np.asarray(qlens, np.int32)
        ref = paged_attention_append(qa, kp.copy(), vp.copy(), tables,
                                     lens, qlens, nk, nv)
        got = paged_attention_append_tp(qa, kp.copy(), vp.copy(), tables,
                                        lens, qlens, nk, nv, tp_mesh)
        # padding rows (>= q_lens) hold garbage in BOTH paths: compare
        # only the valid region of the attention output, pools fully
        valid = np.arange(S)[None, :] < qlens[:, None]
        np.testing.assert_allclose(
            np.asarray(ref[0])[valid], np.asarray(got[0])[valid],
            rtol=1e-5, atol=1e-5)
        for r, g in zip(ref[1:], got[1:]):
            np.testing.assert_allclose(np.asarray(r), np.asarray(g),
                                       rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# Level 2 — the ReplicaRouter
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def router_model():
    return _build_model()


@pytest.fixture(scope="module")
def router_ref_eng(router_model):
    """ONE parity-reference engine for all router tests (compiles once;
    a drained engine is reusable — the test_serving idiom)."""
    return LLMEngine(router_model, max_batch=2, max_seq_len=64,
                     chunk_size=16)


def _ref_tokens(ref_eng, prompts, n):
    assert all(s is None for s in ref_eng.slots) and not ref_eng.waiting
    outs = ref_eng.generate(prompts, max_new_tokens=n)
    return [o.token_ids for o in outs]


def _replica(model, i, fault_injector=None, **kw):
    srv_kw = {k: kw.pop(k) for k in ("step_timeout_s", "supervise")
              if k in kw}
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("chunk_size", 16)
    eng = LLMEngine(model, cache_impl="paged", block_size=8,
                    scheduler="fused", enable_prefix_cache=True, **kw)
    return AsyncLLMServer(eng, max_queue_size=8, replica=i,
                          flight_recorder=True,
                          fault_injector=fault_injector, **srv_kw)


def _shared_prompts(seed, sys_len, tail_sizes):
    rng = np.random.default_rng(seed)
    sysp = rng.integers(1, V, size=(sys_len,)).astype(np.int32)
    return [np.concatenate([sysp,
                            rng.integers(1, V, size=(n,)).astype(np.int32)])
            for n in tail_sizes]


def _throttle(engine, dt=0.01):
    """Slow an engine's readout so scheduling races in the tests become
    deterministic (a queued request must still be queued when the test
    acts on it)."""
    orig = engine.step_finish
    engine.step_finish = lambda p: (time.sleep(dt), orig(p))[1]


def test_probe_prefix_len_read_only(router_model):
    """The router's affinity probe reports the cached prefix without
    touching allocator state (no refcount bumps, no table writes)."""
    eng = LLMEngine(router_model, max_batch=2, max_seq_len=64,
                    chunk_size=16, cache_impl="paged", block_size=8,
                    scheduler="fused", enable_prefix_cache=True)
    prompts = _shared_prompts(5, 24, (5,))
    eng.generate(prompts, max_new_tokens=4)
    before = (list(eng._free_blocks), list(eng._block_ref))
    hit = eng.probe_prefix_len(prompts[0])
    # the 29-token prompt registered its 3 full blocks (8 each)
    assert hit == 24
    assert eng.probe_prefix_len(prompts[0][:17]) == 16
    # the router's precomputed-hash form answers identically (one hash
    # walk per submission, membership tests per replica)
    hashes = eng.prefix_chain_hashes(prompts[0])
    assert len(hashes) == 3
    assert eng.probe_prefix_len(prompts[0], chain_hashes=hashes) == 24
    # a foreign prompt misses
    assert eng.probe_prefix_len(np.arange(1, 40, dtype=np.int32)) == 0
    after = (list(eng._free_blocks), list(eng._block_ref))
    assert before == after
    eng._check_pool_invariants()
    # dense / cache-off engines answer 0 (router falls back to load)
    dense = LLMEngine(router_model, max_batch=1, max_seq_len=64,
                      chunk_size=16)
    assert dense.probe_prefix_len(prompts[0]) == 0


def test_router_affinity_placement(router_model, router_ref_eng):
    """A request sharing a cached system prompt routes to the replica
    that holds it; the placement decision is observable on
    ServeResult.routing (replica, score, affinity_tokens, routing_key)
    and in the request's trace."""
    prompts = _shared_prompts(0, 24, (5, 7, 3))
    want = _ref_tokens(router_ref_eng, prompts, 6)

    router = ReplicaRouter([_replica(router_model, 0),
                            _replica(router_model, 1)])
    router.start()
    try:
        r0 = router.submit(prompts[0], max_new_tokens=6).result(timeout=300)
        seeded = r0.routing["replica"]
        assert r0.routing["affinity_tokens"] == 0  # cold cluster
        r1 = router.submit(prompts[1], max_new_tokens=6,
                           routing_key="tenantA").result(timeout=300)
        assert r1.routing["replica"] == seeded
        assert r1.routing["affinity_tokens"] == 24
        assert r1.routing["routing_key"] == "tenantA"
        assert r1.routing["policy"] == "affinity"
        # trace carries the placement as a "routed" span
        kinds = [e["kind"] for e in r1.trace["events"]]
        assert "routed" in kinds
        # token-exactness through the router
        assert [r0.token_ids, r1.token_ids] == want[:2]
        # streaming iteration through the RouterHandle
        h2 = router.submit(prompts[2], max_new_tokens=6)
        assert list(h2) == want[2]
        assert router.stats["affinity_routed"] >= 1
    finally:
        router.stop()


@pytest.mark.slow
def test_router_least_loaded_spreads(router_model):
    """Without affinity signal, placement balances by the load gauges:
    two concurrent requests on two single-slot replicas land on
    DIFFERENT replicas."""
    srv0 = _replica(router_model, 0, max_batch=1)
    srv1 = _replica(router_model, 1, max_batch=1)
    router = ReplicaRouter([srv0, srv1], policy="least_loaded")
    router.start()
    try:
        _throttle(srv0.engine)
        _throttle(srv1.engine)
        prompts = _prompts(9, (9, 9))
        h0 = router.submit(prompts[0], max_new_tokens=12)
        # let the gauges see replica 0 busy before placing the second
        time.sleep(0.15)
        h1 = router.submit(prompts[1], max_new_tokens=12)
        h0.result(timeout=300), h1.result(timeout=300)
        assert {h0.replica, h1.replica} == {0, 1}
        assert router.stats["placements"] == [1, 1]
    finally:
        router.stop()


def test_router_failover_mid_stream(router_model, router_ref_eng):
    """Kill a replica mid-stream under load (a scripted
    FaultInjector.kill(), not ad-hoc thread murder): its QUEUED requests
    complete on the survivor with the exact tokens a healthy serve
    produces, its IN-FLIGHT request fails with
    finish_reason="replica_lost" (carrying the tokens streamed so far),
    and the survivor's pool invariants hold (PADDLE_TPU_POOL_CHECKS is
    armed suite-wide)."""
    prompts = _shared_prompts(1, 16, (5, 7, 3))
    want = _ref_tokens(router_ref_eng, prompts, 6)

    fi0 = FaultInjector()
    srv0 = _replica(router_model, 0, fault_injector=fi0, max_batch=1)
    srv1 = _replica(router_model, 1)
    router = ReplicaRouter([srv0, srv1])
    router.start()
    try:
        _throttle(srv0.engine)  # keep the victim streaming slowly
        # in-flight on the doomed replica, queued behind its sole slot
        h_live = router.submit(prompts[0], max_new_tokens=30, replica=0)
        h_q1 = router.submit(prompts[1], max_new_tokens=6, replica=0)
        h_q2 = router.submit(prompts[2], max_new_tokens=6, replica=0)
        stream = iter(h_live)
        first = next(stream)          # it is genuinely mid-stream

        fi0.kill("injected replica death")

        lost = h_live.result(timeout=300)
        assert lost.finish_reason == "replica_lost"
        assert lost.token_ids[0] == first
        assert lost.routing["replica"] == 0
        assert lost.trace_ctx is not None and lost.trace_ctx.hop == 0
        # queued requests converted to RESUBMISSION, not loss
        for h, tokens in ((h_q1, want[1]), (h_q2, want[2])):
            res = h.result(timeout=300)
            assert res.finish_reason in ("length", "eos")
            assert res.token_ids == tokens
            assert h.replica == 1
            assert h.resubmits == 1
            assert res.routing["resubmits"] == 1
            # the trace identity survives the failover with exactly one
            # hop bump, attributed to the failover resubmission
            assert res.trace_ctx is not None
            assert res.trace_ctx.hop == 1
            assert res.trace_ctx.via == "failover"
        assert router.stats["replica_lost"] == 1
        assert router.stats["resubmitted"] == 2
        srv1.engine._check_pool_invariants()
        assert not router.alive(0) and router.alive(1)
        # replica-label satellite, on the servers already running here:
        # the survivor's Prometheus lines carry its replica label (so a
        # cluster scrape aggregates instead of colliding) and its
        # snapshot/explain_tail carry the placement record
        text = srv1.telemetry.prometheus_text()
        assert 'replica="1"' in text
        assert 'stage="idle",replica="1"' in text
        assert srv1.telemetry.snapshot()["replica"] == 1
        tail = srv1.flight_recorder.explain_tail(0.0)
        assert tail and all(e["routing"]["resubmits"] == 1 for e in tail)
    finally:
        errors = router.stop()
    # the dead replica's crash surfaces at stop, attributably
    assert [i for i, _ in errors] == [0]
    assert "injected replica death" in str(errors[0][1])


@pytest.mark.slow
def test_router_hung_replica_failover_resume(router_model,
                                             router_ref_eng):
    """Health-probe failover: a replica wedged INSIDE a step (thread
    ALIVE, heartbeat stale past step_timeout_s) flips health() to
    "hung"; the router evicts its residents without waiting for the
    thread to die, and — with resume_inflight=True — the stream
    CONTINUES token-exactly on the survivor from what the caller
    already consumed. Slow lane: the wedge must outlive failover wall
    (seconds) by construction; the tier-1 watchdog/hang coverage lives
    in tests/test_faults.py."""
    prompts = _shared_prompts(3, 16, (5,))
    want = _ref_tokens(router_ref_eng, prompts, 10)
    fi0 = FaultInjector()
    srv0 = _replica(router_model, 0, fault_injector=fi0,
                    step_timeout_s=0.5)
    srv1 = _replica(router_model, 1)
    # warm the compile caches BEFORE arming the tight step_timeout_s —
    # a cold first-step compile would read as a hang
    for srv in (srv0, srv1):
        srv.engine.generate([prompts[0]], max_new_tokens=2)
        srv.engine.reset()
    router = ReplicaRouter([srv0, srv1], resume_inflight=True)
    router.start()
    try:
        h = router.submit(prompts[0], max_new_tokens=10, replica=0)
        first = next(iter(h))
        # long enough that failover (~0.5s stale + resume serve) runs
        # to completion while the victim is still wedged; short enough
        # that the teardown stop() isn't parked long once it ends
        fi0.hang_at_step(5, seconds=3.5, interruptible=False)
        res = h.result(timeout=300)
        # the wedged replica was failed over AROUND, not waited out
        assert res.finish_reason in ("length", "eos")
        assert res.token_ids == want[0]
        assert res.token_ids[0] == first
        assert h.replica == 1 and h.resubmits == 1
        assert router.stats["evicted_hung"] >= 1
        assert router.stats["resumed"] >= 1
        # the thread is still alive — this was a HEALTH failover
        assert router.alive(0) and not router.healthy(0)
        assert srv0.health()["state"] == "hung"
        # the gauge flips on the next watchdog tick (<= timeout/4 after
        # the heartbeat goes stale) — the router's health() age check
        # can legitimately beat it by one tick
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                srv0.telemetry.get_gauges()["server_healthy"] != 0.0:
            time.sleep(0.01)
        assert srv0.telemetry.get_gauges()["server_healthy"] == 0.0
        srv1.engine._check_pool_invariants()
    finally:
        router.stop(timeout=120)


@pytest.mark.slow
def test_router_supervised_replica_recovers_in_place(router_model,
                                                     router_ref_eng):
    """A SUPERVISED replica's crash is not a failover event: health
    reports "restarting" (no new placements, residents stay), the
    restart resumes every stream in place, and the router's
    resubmission machinery never fires. Slow lane: single-server
    supervised recovery is tier-1-covered in tests/test_faults.py;
    this adds the through-the-router angle."""
    prompts = _shared_prompts(9, 16, (5, 7))
    want = _ref_tokens(router_ref_eng, prompts, 6)
    fi0 = FaultInjector().crash_at_step(3)
    srv0 = _replica(router_model, 0, fault_injector=fi0,
                    supervise=RestartPolicy(max_restarts=1,
                                            backoff_s=0.01))
    srv1 = _replica(router_model, 1)
    router = ReplicaRouter([srv0, srv1])
    router.start()
    try:
        hs = [router.submit(p, max_new_tokens=6, replica=0)
              for p in prompts]
        results = [h.result(timeout=300) for h in hs]
        assert [r.token_ids for r in results] == want
        assert all(h.replica == 0 and h.resubmits == 0 for h in hs)
        assert srv0.restarts == 1
        assert router.stats["resubmitted"] == 0
        assert router.stats["replica_lost"] == 0
        srv0.engine._check_pool_invariants()
    finally:
        router.stop()


@pytest.mark.slow
def test_chaos_soak_three_replicas(router_model, router_ref_eng):
    """The scripted-chaos soak the ISSUE asks for: a seeded random
    fault schedule (crashes + sub-watchdog hangs) over 3 supervised
    replicas under mixed load. Every stream either finishes
    TOKEN-EXACTLY (in-place restart or resume_inflight failover) or
    fails attributably; pool invariants hold everywhere
    (PADDLE_TPU_POOL_CHECKS armed suite-wide)."""
    rng = np.random.default_rng(42)
    prompts = _shared_prompts(10, 24, tuple(3 + i % 9 for i in range(18)))
    want = _ref_tokens(router_ref_eng, prompts, 8)
    fis = [FaultInjector() for _ in range(3)]
    replicas = [_replica(router_model, i, fault_injector=fis[i],
                         supervise=RestartPolicy(max_restarts=3,
                                                 backoff_s=0.01),
                         step_timeout_s=5.0)
                for i in range(3)]
    for srv in replicas:   # compile before the watchdog arms
        srv.engine.generate([prompts[0][:8]], max_new_tokens=2)
        srv.engine.reset()
    # the scripted "random" schedule: deterministic under the seed, so
    # a failure replays exactly
    for fi in fis:
        for step in sorted(int(s) for s in rng.integers(2, 40, size=3)):
            if rng.random() < 0.5:
                fi.crash_at_step(step)
            else:
                fi.hang_at_step(step, seconds=0.2)
    router = ReplicaRouter(replicas, resume_inflight=True)
    router.start()
    try:
        handles = [router.submit(p, max_new_tokens=8) for p in prompts]
        results = [h.result(timeout=600) for h in handles]
        exact = 0
        for r, tokens in zip(results, want):
            if r.finish_reason in ("length", "eos"):
                assert r.token_ids == tokens
                exact += 1
            else:   # attributable, never silent
                assert r.finish_reason in ("replica_lost",), r
        assert exact >= len(prompts) - 2    # chaos, not carnage
        assert sum(len(fi.fired) for fi in fis) >= 3
        for srv in replicas:
            if srv._crashed is None:
                srv.engine._check_pool_invariants()
    finally:
        router.stop(timeout=120)


def test_router_drain_migrates_queued(router_model, router_ref_eng):
    """drain(): the replica stops taking new work, queued requests
    migrate to survivors, running ones finish in place."""
    prompts = _shared_prompts(2, 16, (5, 7))
    want = _ref_tokens(router_ref_eng, prompts, 6)

    srv0 = _replica(router_model, 0, max_batch=1)
    srv1 = _replica(router_model, 1)
    router = ReplicaRouter([srv0, srv1])
    router.start()
    try:
        _throttle(srv0.engine)
        h_run = router.submit(prompts[0], max_new_tokens=25, replica=0)
        h_q = router.submit(prompts[1], max_new_tokens=6, replica=0)
        next(iter(h_run))             # running and streaming
        router.drain(0, timeout=120)
        run_res = h_run.result(timeout=300)
        assert run_res.finish_reason in ("length", "eos")
        assert len(run_res.token_ids) == 25      # finished in place
        q_res = h_q.result(timeout=300)
        assert q_res.token_ids == want[1]
        assert q_res.routing["replica"] == 1     # migrated
        assert not router.alive(0) and router.alive(1)
        # a drained replica receives no new placements
        h_new = router.submit(prompts[0], max_new_tokens=4)
        assert h_new.result(timeout=300).routing["replica"] == 1
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# multi-replica observability (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_replica_labels_and_merged_trace(tmp_path, router_model):
    """Replica-labeled Prometheus lines don't collide across replicas,
    snapshots carry the index, explain_tail entries carry the routing
    record, and the merged chrome trace lands one process lane group per
    replica."""
    import json

    prompts = _shared_prompts(4, 16, (5, 7))
    router = ReplicaRouter([_replica(router_model, 0),
                            _replica(router_model, 1)])
    router.start()
    try:
        hs = [router.submit(p, max_new_tokens=6, replica=i % 2,
                            routing_key=f"t{i}")
              for i, p in enumerate(prompts)]
        for h in hs:
            h.result(timeout=300)
        text = router.prometheus_text()
        assert 'replica="0"' in text and 'replica="1"' in text
        # valid exposition: ONE TYPE line per metric family, every
        # replica's labeled samples grouped under it (strict parsers
        # reject repeated TYPE lines / split families)
        fam = "paddle_tpu_serving_requests_finished_total"
        assert text.count(f"# TYPE {fam}") == 1
        assert text.count(f'{fam}{{replica="0"}}') == 1
        assert text.count(f'{fam}{{replica="1"}}') == 1
        assert 'stage="idle",replica="0"' in text
        snap = router.snapshot()
        assert snap["replicas"][0]["telemetry"]["replica"] == 0
        # explain_tail carries the placement record on tail entries
        tail = router.replicas[0].flight_recorder.explain_tail(0.0)
        assert tail and all(e["routing"]["replica"] == 0 for e in tail)
        merged = router.export_merged_trace(
            str(tmp_path / "cluster_trace.json"))
        events = json.load(open(merged))["traceEvents"]
        names = {e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert {"rank0:replica0", "rank1:replica1"} <= names
        pids = {e["pid"] for e in events}
        assert pids == {0, 1}
    finally:
        router.stop()


def test_routing_metadata_plain_server(router_model):
    """The routing satellite works WITHOUT the router: submit(...,
    routing=...) surfaces on ServeResult and in the trace on a plain
    AsyncLLMServer."""
    eng = LLMEngine(router_model, max_batch=1, max_seq_len=64,
                    chunk_size=16)
    server = AsyncLLMServer(eng, max_queue_size=4, flight_recorder=True)
    server.start()
    try:
        h = server.submit(np.arange(1, 8, dtype=np.int32),
                          max_new_tokens=4,
                          routing={"routing_key": "abc", "shard": 3})
        res = h.result(timeout=300)
        assert res.routing == {"routing_key": "abc", "shard": 3}
        routed = [e for e in res.trace["events"] if e["kind"] == "routed"]
        assert routed and routed[0]["value"]["shard"] == 3
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# big mesh / soak (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_tp8_engine_parity():
    """Full 8-device TP parity (the MULTICHIP dryrun's serve=engine_tp(8)
    shape, single process)."""
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    paddle.seed(7)
    cfg = LlamaConfig(vocab_size=V, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=8, max_position_embeddings=128)
    ref_m = LlamaForCausalLM(cfg)
    ref_m.eval()
    paddle.seed(7)
    tp_m = LlamaForCausalLM(cfg)
    tp_m.eval()
    prompts = _prompts(3, (9, 5))
    ref = LLMEngine(ref_m, max_batch=2, max_seq_len=64, chunk_size=16,
                    cache_impl="paged", block_size=8, scheduler="fused")
    want = [o.token_ids for o in ref.generate(prompts, max_new_tokens=8)]
    mesh = Mesh(np.asarray(devs[:8]), ("tp",))
    eng = tp_engine(tp_m, mesh=mesh, max_batch=2, max_seq_len=64,
                    chunk_size=16, cache_impl="paged", block_size=8,
                    scheduler="fused")
    assert eng.tp_degree() == 8
    got = [o.token_ids for o in eng.generate(prompts, max_new_tokens=8)]
    assert got == want


@pytest.mark.slow
def test_failover_retries_through_full_survivor_queue(router_model,
                                                      router_ref_eng):
    """A survivor whose admission queue is momentarily FULL must not
    convert a failover resubmission into request loss — the router parks
    the handle and retries on monitor ticks until the queue frees
    (failover_retry_s window)."""
    prompts = _shared_prompts(6, 16, (5, 7, 3, 4))
    want = _ref_tokens(router_ref_eng, prompts, 4)
    fi0 = FaultInjector()
    srv0 = _replica(router_model, 0, fault_injector=fi0, max_batch=1)
    srv1 = AsyncLLMServer(
        LLMEngine(router_model, max_batch=1, max_seq_len=64,
                  chunk_size=16, cache_impl="paged", block_size=8,
                  scheduler="fused", enable_prefix_cache=True),
        max_queue_size=1, replica=1)
    router = ReplicaRouter([srv0, srv1], failover_retry_s=60.0)
    router.start()
    try:
        _throttle(srv0.engine)
        _throttle(srv1.engine)
        # survivor: one running (slot), one in engine.waiting, one
        # FILLING its single admission-queue slot
        s_run = router.submit(prompts[0], max_new_tokens=25, replica=1)
        next(iter(s_run))
        s_w = router.submit(prompts[1], max_new_tokens=4, replica=1)
        s_q = router.submit(prompts[2], max_new_tokens=4, replica=1)
        # victim: one queued request, then crash
        h_q = router.submit(prompts[3], max_new_tokens=4, replica=0)

        fi0.kill("injected replica death")
        res = h_q.result(timeout=300)
        assert res.finish_reason in ("length", "eos")
        assert res.token_ids == want[3]
        assert h_q.replica == 1 and h_q.resubmits == 1
        for h, tokens in ((s_w, want[1]), (s_q, want[2])):
            assert h.result(timeout=300).token_ids == tokens
        s_run.result(timeout=300)
    finally:
        router.stop()


@pytest.mark.slow
def test_router_soak_under_churn(router_model):
    """Sustained mixed load across 3 replicas with a mid-run drain:
    every request finishes (complete or attributably migrated), pool
    invariants hold everywhere."""
    prompts = _shared_prompts(8, 24, tuple(3 + i % 9 for i in range(24)))
    replicas = [_replica(router_model, i) for i in range(3)]
    router = ReplicaRouter(replicas)
    router.start()
    try:
        handles = [router.submit(p, max_new_tokens=8) for p in prompts[:16]]
        router.drain(0, timeout=300)
        handles += [router.submit(p, max_new_tokens=8)
                    for p in prompts[16:]]
        results = [h.result(timeout=600) for h in handles]
        assert all(r.finish_reason in ("length", "eos", "cancelled")
                   for r in results)
        for srv in replicas[1:]:
            srv.engine._check_pool_invariants()
    finally:
        router.stop()
