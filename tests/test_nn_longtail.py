"""nn/nn.functional long tail: unpool, fractional pool, grid_sample,
adaptive softmax, hsigmoid, rnnt, margin losses, beam search decode.

torch (CPU) is the numeric ground truth where the op follows a published
formulation shared by the reference's phi kernels.
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")


def t2n(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


# -- pooling ------------------------------------------------------------------

def test_max_unpool2d_matches_torch(rng):
    x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
    pooled, idx = F.max_pool2d(paddle.to_tensor(x), 2, 2, return_mask=True)
    tp, ti = torch.nn.functional.max_pool2d(
        torch.tensor(x), 2, 2, return_indices=True)
    np.testing.assert_allclose(t2n(pooled), tp.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(t2n(idx), ti.numpy())
    un = F.max_unpool2d(pooled, idx, 2, 2)
    tun = torch.nn.functional.max_unpool2d(tp, ti, 2, 2)
    np.testing.assert_allclose(t2n(un), tun.numpy(), rtol=1e-6)


def test_max_pool_mask_exact_beyond_float24_boundary(rng):
    """Regression (ADVICE r5): the return_mask indices used to ride
    through reduce_window as float32, which is only integer-exact up to
    2**24 — on spatial sizes past ~16.7M elements the returned argmax
    positions silently rounded to even values. Indices are now int32;
    the window maxima here sit at ODD flat positions past 2**24, which
    the float32 carry could not represent."""
    H, W = 4099, 4098  # H*W = 16,797,702 > 2**24 = 16,777,216
    # max of every 2x2 window at its odd-odd corner -> odd flat index
    col = (np.arange(W, dtype=np.float32) % 2)
    row = (np.arange(H, dtype=np.float32) % 2)
    x = (row[:, None] + col[None, :]).reshape(1, 1, H, W)
    _, idx = F.max_pool2d(paddle.to_tensor(x), 2, 2, return_mask=True)
    idx = t2n(idx)
    r, c = idx.shape[2] - 1, idx.shape[3] - 1  # bottom-right window
    expect = (2 * r + 1) * W + (2 * c + 1)
    assert expect > 2 ** 24
    assert idx[0, 0, r, c] == expect
    assert idx[0, 0, r, c] % 2 == 1  # odd: unrepresentable in f32 there
    # spot-check a row of windows past the boundary
    rows = 2 * np.arange(idx.shape[2]) + 1
    np.testing.assert_array_equal(
        idx[0, 0, :, c], rows * W + (2 * c + 1))


def test_max_unpool_layer_and_output_size(rng):
    x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
    pooled, idx = F.max_pool2d(paddle.to_tensor(x), 2, 2, return_mask=True)
    out = nn.MaxUnPool2D(2, 2, output_size=[1, 2, 6, 6])(pooled, idx)
    assert out.shape == [1, 2, 6, 6]


def test_lp_pool1d_is_p_norm_pool(rng):
    x = rng.standard_normal((2, 3, 10)).astype(np.float32)
    ours = t2n(F.lp_pool1d(paddle.to_tensor(x), 2.0, 2, 2))
    ref = torch.nn.functional.lp_pool1d(torch.tensor(x), 2.0, 2, 2).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_fractional_max_pool2d_windows(rng):
    x = rng.standard_normal((1, 1, 9, 9)).astype(np.float32)
    out, mask = F.fractional_max_pool2d(paddle.to_tensor(x), 4, random_u=0.3,
                                        return_mask=True)
    assert t2n(out).shape == (1, 1, 4, 4)
    # every output must be the max of SOME contiguous window, and the mask
    # must point at exactly that element
    ov, mv = t2n(out), t2n(mask)
    flat = x[0, 0].ravel()
    np.testing.assert_allclose(ov[0, 0].ravel(), flat[mv[0, 0].ravel()])


def test_fractional_max_pool3d_shape(rng):
    x = rng.standard_normal((1, 2, 8, 8, 8)).astype(np.float32)
    out = F.fractional_max_pool3d(paddle.to_tensor(x), 3, random_u=0.7)
    assert t2n(out).shape == (1, 2, 3, 3, 3)


# -- vision ops ---------------------------------------------------------------

@pytest.mark.parametrize("align", [True, False])
@pytest.mark.parametrize("mode", ["bilinear", "nearest"])
@pytest.mark.parametrize("pad", ["zeros", "border", "reflection"])
def test_grid_sample_matches_torch(rng, align, mode, pad):
    x = rng.standard_normal((2, 3, 5, 7)).astype(np.float32)
    grid = (rng.random((2, 4, 6, 2)).astype(np.float32) * 2.4 - 1.2)
    ours = t2n(F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                             mode=mode, padding_mode=pad, align_corners=align))
    ref = torch.nn.functional.grid_sample(
        torch.tensor(x), torch.tensor(grid), mode=mode, padding_mode=pad,
        align_corners=align).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


def test_grid_sample_3d_matches_torch(rng):
    x = rng.standard_normal((1, 2, 4, 5, 6)).astype(np.float32)
    grid = (rng.random((1, 3, 4, 5, 3)).astype(np.float32) * 2 - 1)
    ours = t2n(F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                             align_corners=True))
    ref = torch.nn.functional.grid_sample(
        torch.tensor(x), torch.tensor(grid), align_corners=True).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("align", [True, False])
def test_affine_grid_matches_torch(rng, align):
    theta = rng.standard_normal((2, 2, 3)).astype(np.float32)
    ours = t2n(F.affine_grid(paddle.to_tensor(theta), [2, 3, 4, 5],
                             align_corners=align))
    ref = torch.nn.functional.affine_grid(
        torch.tensor(theta), [2, 3, 4, 5], align_corners=align).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_grid_sample_gradient_flows(rng):
    x = paddle.to_tensor(rng.standard_normal((1, 1, 4, 4)).astype(np.float32),
                         stop_gradient=False)
    g = paddle.to_tensor((rng.random((1, 2, 2, 2)).astype(np.float32) - 0.5),
                         stop_gradient=False)
    out = F.grid_sample(x, g)
    out.sum().backward()
    assert x.grad is not None and np.isfinite(t2n(x.grad)).all()
    assert g.grad is not None and np.isfinite(t2n(g.grad)).all()


# -- extension ops ------------------------------------------------------------

def test_sequence_mask():
    m = F.sequence_mask(paddle.to_tensor(np.array([1, 3, 2])), maxlen=4)
    np.testing.assert_array_equal(
        t2n(m), [[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])


def test_temporal_shift_semantics():
    # N=1, T=2 segments, C=4, 1x1 spatial; shift_ratio=0.25 → 1 fwd, 1 bwd chan
    x = np.arange(8, dtype=np.float32).reshape(2, 4, 1, 1)
    out = t2n(F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                               shift_ratio=0.25))
    # channel 0: shifted from t-1 (t0 gets 0, t1 gets t0's value)
    assert out[0, 0, 0, 0] == 0.0 and out[1, 0, 0, 0] == x[0, 0, 0, 0]
    # channel 1: shifted from t+1
    assert out[0, 1, 0, 0] == x[1, 1, 0, 0] and out[1, 1, 0, 0] == 0.0
    # channels 2-3 unchanged
    np.testing.assert_array_equal(out[:, 2:], x[:, 2:])


def test_gather_tree_reference_example():
    ids = paddle.to_tensor(np.array(
        [[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]], np.int64))
    parents = paddle.to_tensor(np.array(
        [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]], np.int64))
    out = t2n(F.gather_tree(ids, parents))
    np.testing.assert_array_equal(
        out, [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]])


def test_class_center_sample():
    label = paddle.to_tensor(np.array([0, 5, 5, 9], np.int64))
    remapped, sampled = F.class_center_sample(label, 20, 6)
    sv, rv = t2n(sampled), t2n(remapped)
    assert len(sv) == 6 and set([0, 5, 9]) <= set(sv.tolist())
    # remapped labels index into sampled
    np.testing.assert_array_equal(sv[rv], [0, 5, 5, 9])


def test_sparse_attention_matches_dense(rng):
    B, H, S, D = 1, 2, 4, 8
    q = rng.standard_normal((B, H, S, D)).astype(np.float32)
    k = rng.standard_normal((B, H, S, D)).astype(np.float32)
    v = rng.standard_normal((B, H, S, D)).astype(np.float32)
    # full (dense) CSR pattern → must equal plain softmax attention
    offs = np.tile(np.arange(0, S * S + 1, S, dtype=np.int32), (B, H, 1))
    cols = np.tile(np.tile(np.arange(S, dtype=np.int32), S), (B, H, 1))
    out = t2n(F.sparse_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v), paddle.to_tensor(offs),
                                 paddle.to_tensor(cols)))
    qt, kt, vt = map(torch.tensor, (q, k, v))
    ref = torch.nn.functional.scaled_dot_product_attention(qt, kt, vt).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


# -- losses -------------------------------------------------------------------

def test_multi_margin_loss_matches_torch(rng):
    x = rng.standard_normal((5, 7)).astype(np.float32)
    y = rng.integers(0, 7, 5)
    w = rng.random(7).astype(np.float32)
    for p in (1, 2):
        ours = t2n(F.multi_margin_loss(paddle.to_tensor(x),
                                       paddle.to_tensor(y), p=p, margin=0.8,
                                       weight=paddle.to_tensor(w)))
        ref = torch.nn.functional.multi_margin_loss(
            torch.tensor(x), torch.tensor(y), p=p, margin=0.8,
            weight=torch.tensor(w)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_dice_loss_formula(rng):
    probs = rng.random((3, 4, 5)).astype(np.float32)
    lbl = rng.integers(0, 5, (3, 4, 1))
    ours = float(t2n(F.dice_loss(paddle.to_tensor(probs),
                                 paddle.to_tensor(lbl))))
    oh = np.eye(5, dtype=np.float32)[lbl[..., 0]]
    inse = (probs * oh).sum(axis=(1, 2))
    denom = probs.sum(axis=(1, 2)) + oh.sum(axis=(1, 2))
    exp = float(np.mean(1 - 2 * inse / (denom + 1e-5)))
    assert abs(ours - exp) < 1e-6


def test_npair_loss_runs(rng):
    a = rng.random((6, 4)).astype(np.float32)
    p = rng.random((6, 4)).astype(np.float32)
    lab = rng.integers(0, 3, 6).astype(np.float32)
    out = float(t2n(F.npair_loss(paddle.to_tensor(a), paddle.to_tensor(p),
                                 paddle.to_tensor(lab))))
    assert np.isfinite(out) and out > 0


def test_hsigmoid_loss_matches_bitcode_reference(rng):
    # brute-force SimpleCode reimplementation (matrix_bit_code.h)
    N, feat, C = 4, 6, 7
    x = rng.standard_normal((N, feat)).astype(np.float32)
    y = rng.integers(0, C, N)
    w = rng.standard_normal((C - 1, feat)).astype(np.float32)
    b = rng.standard_normal((C - 1, 1)).astype(np.float32)
    ours = t2n(F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(y), C,
                               paddle.to_tensor(w), paddle.to_tensor(b)))
    exp = np.zeros((N, 1), np.float32)
    for i in range(N):
        c = int(y[i]) + C
        length = c.bit_length() - 1
        for bit in range(length):
            idx = (c >> (bit + 1)) - 1
            tgt = (c >> bit) & 1
            z = float(w[idx] @ x[i] + b[idx, 0])
            exp[i, 0] += np.log1p(np.exp(z)) - tgt * z
    np.testing.assert_allclose(ours, exp, rtol=1e-4, atol=1e-5)


def test_margin_cross_entropy_arcface(rng):
    N, C = 4, 6
    logits = np.clip(rng.standard_normal((N, C)), -0.99, 0.99).astype(np.float32)
    y = rng.integers(0, C, N)
    loss, sm = F.margin_cross_entropy(
        paddle.to_tensor(logits), paddle.to_tensor(y), margin1=1.0, margin2=0.5,
        margin3=0.0, scale=64.0, return_softmax=True, reduction=None)
    # manual
    mod = logits.copy().astype(np.float64)
    for i in range(N):
        th = np.arccos(np.clip(logits[i, y[i]], -1, 1))
        mod[i, y[i]] = np.cos(th + 0.5)
    mod *= 64.0
    p = np.exp(mod - mod.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    exp = -np.log(p[np.arange(N), y])[:, None]
    np.testing.assert_allclose(t2n(loss), exp, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(t2n(sm), p, rtol=1e-4, atol=1e-5)


def test_adaptive_log_softmax_matches_torch(rng):
    N, in_f, C = 6, 8, 12
    cutoffs = [4, 8]
    x = rng.standard_normal((N, in_f)).astype(np.float32)
    y = rng.integers(0, C, N)
    layer = nn.AdaptiveLogSoftmaxWithLoss(in_f, C, cutoffs, div_value=2.0,
                                          head_bias=True)
    tl = torch.nn.AdaptiveLogSoftmaxWithLoss(in_f, C, cutoffs, div_value=2.0,
                                             head_bias=True)
    # copy our params into torch (torch Linear stores [out, in])
    with torch.no_grad():
        tl.head.weight.copy_(torch.tensor(t2n(layer.head_weight).T))
        tl.head.bias.copy_(torch.tensor(t2n(layer.head_bias)))
        for i, (proj, cls_w) in enumerate(layer.tail_weights):
            tl.tail[i][0].weight.copy_(torch.tensor(t2n(proj).T))
            tl.tail[i][1].weight.copy_(torch.tensor(t2n(cls_w).T))
    out, loss = layer(paddle.to_tensor(x), paddle.to_tensor(y))
    with torch.no_grad():
        tout = tl(torch.tensor(x), torch.tensor(y))
    np.testing.assert_allclose(t2n(out), tout.output.numpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(t2n(loss)), float(tout.loss), rtol=1e-4)
    # full log-prob path
    np.testing.assert_allclose(t2n(layer.log_prob(paddle.to_tensor(x))),
                               tl.log_prob(torch.tensor(x)).detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def _brute_force_rnnt(logp, labels, blank):
    # enumerate all monotonic alignments by DP in plain python (ground truth)
    T, U, V = logp.shape
    import functools

    @functools.lru_cache(None)
    def alpha(t, u):
        if t == 0 and u == 0:
            return 0.0
        terms = []
        if t > 0:
            terms.append(alpha(t - 1, u) + logp[t - 1, u, blank])
        if u > 0:
            terms.append(alpha(t, u - 1) + logp[t, u - 1, labels[u - 1]])
        m = max(terms)
        return m + np.log(sum(np.exp(x - m) for x in terms))

    return -(alpha(T - 1, U - 1) + logp[T - 1, U - 1, blank])


def test_rnnt_loss_matches_bruteforce(rng):
    B, T, U, V = 2, 4, 3, 5
    logits = rng.standard_normal((B, T, U, V)).astype(np.float32)
    labels = rng.integers(1, V, (B, U - 1))
    in_len = np.array([T, T - 1])
    lbl_len = np.array([U - 1, U - 2])
    ours = t2n(F.rnnt_loss(paddle.to_tensor(logits),
                           paddle.to_tensor(labels.astype(np.int32)),
                           paddle.to_tensor(in_len.astype(np.int32)),
                           paddle.to_tensor(lbl_len.astype(np.int32)),
                           blank=0, reduction="none"))
    logp = np.asarray(jnp.log(jnp.asarray(
        np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))))
    for b in range(B):
        Tb, Ub = in_len[b], lbl_len[b] + 1
        exp = _brute_force_rnnt(logp[b, :Tb, :Ub], labels[b], 0)
        assert abs(float(ours[b]) - exp) < 1e-4


def test_rnnt_loss_layer_gradient(rng):
    logits = paddle.to_tensor(
        rng.standard_normal((1, 3, 3, 4)).astype(np.float32),
        stop_gradient=False)
    loss = nn.RNNTLoss()(logits, paddle.to_tensor(np.array([[1, 2]], np.int32)),
                         paddle.to_tensor(np.array([3], np.int32)),
                         paddle.to_tensor(np.array([2], np.int32)))
    loss.backward()
    assert np.isfinite(t2n(logits.grad)).all()


# -- in-place activations -----------------------------------------------------

def test_inplace_activations(rng):
    x = paddle.to_tensor(rng.standard_normal(5).astype(np.float32))
    before = t2n(x).copy()
    r = F.relu_(x)
    assert r is x
    np.testing.assert_allclose(t2n(x), np.maximum(before, 0))
    y = paddle.to_tensor(np.array([-2.0, 0.5, 3.0], np.float32))
    F.hardtanh_(y)
    np.testing.assert_allclose(t2n(y), [-1.0, 0.5, 1.0])


# -- beam search --------------------------------------------------------------

def test_beam_search_decoder_greedy_consistency(rng):
    # beam_size=1 must reproduce the greedy argmax rollout
    vocab, hidden, batch = 7, 8, 2
    cell = nn.GRUCell(hidden, hidden)
    emb_w = paddle.to_tensor(rng.standard_normal((vocab, hidden))
                             .astype(np.float32))
    out_w = paddle.to_tensor(rng.standard_normal((hidden, vocab))
                             .astype(np.float32))

    def embedding_fn(ids):
        return paddle.to_tensor(jnp.take(emb_w._value, ids._value, axis=0))

    def output_fn(h):
        return h @ paddle.to_tensor(out_w._value)

    decoder = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                   beam_size=1, embedding_fn=embedding_fn,
                                   output_fn=output_fn)
    h0 = paddle.to_tensor(rng.standard_normal((batch, hidden))
                          .astype(np.float32))
    outs, final_states = nn.dynamic_decode(decoder, inits=h0, max_step_num=5)
    ids = t2n(outs.predicted_ids)  # (batch, T, beam)
    # greedy rollout
    h = np.asarray(h0._value)
    tok = np.zeros((batch,), np.int64)
    for step in range(ids.shape[1]):
        inp = paddle.to_tensor(np.asarray(emb_w._value)[tok])
        hout, hnew = cell(inp, paddle.to_tensor(h))
        logits = t2n(hout @ paddle.to_tensor(out_w._value))
        nxt = logits.argmax(-1)
        # finished sequences emit end_token forever
        done = tok == 1
        nxt = np.where(done, 1, nxt)
        np.testing.assert_array_equal(ids[:, step, 0], nxt)
        h = np.where(done[:, None], h, t2n(hnew))
        tok = nxt


def test_beam_search_beam2_scores_sorted(rng):
    vocab, hidden = 5, 6
    cell = nn.GRUCell(hidden, hidden)
    emb_w = paddle.to_tensor(rng.standard_normal((vocab, hidden))
                             .astype(np.float32))
    out_w = paddle.to_tensor(rng.standard_normal((hidden, vocab))
                             .astype(np.float32))
    decoder = nn.BeamSearchDecoder(
        cell, start_token=0, end_token=1, beam_size=2,
        embedding_fn=lambda ids: paddle.to_tensor(
            jnp.take(emb_w._value, ids._value, axis=0)),
        output_fn=lambda h: h @ paddle.to_tensor(out_w._value))
    h0 = paddle.to_tensor(rng.standard_normal((1, hidden)).astype(np.float32))
    outs, _, lengths = nn.dynamic_decode(decoder, inits=h0, max_step_num=4,
                                         return_length=True)
    scores = t2n(outs.scores)  # (batch, T, beam)
    assert (scores[:, -1, 0] >= scores[:, -1, 1]).all()
    assert t2n(lengths).max() <= 5


def test_flash_attn_qkvpacked_matches_flash_attention(rng):
    # MHA packing: [B, S, 3, H, D] with q in slot 0, k/v in the LAST two
    B, S, H, D = 2, 6, 2, 8
    q = rng.standard_normal((B, S, H, D)).astype(np.float32)
    k = rng.standard_normal((B, S, H, D)).astype(np.float32)
    v = rng.standard_normal((B, S, H, D)).astype(np.float32)
    qkv = np.stack([q, k, v], axis=2)
    packed, _ = F.flash_attn_qkvpacked(paddle.to_tensor(qkv))
    plain, _ = F.flash_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v))
    np.testing.assert_allclose(t2n(packed), t2n(plain), rtol=1e-5, atol=1e-6)


def test_flash_attn_qkvpacked_gqa_head_mapping(rng):
    # GQA: G=2 groups, Hk=2 kv heads → 4 q heads; flattened q head j attends
    # kv head j // G (FA semantics)
    B, S, G, Hk, D = 1, 5, 2, 2, 4
    qkv = rng.standard_normal((B, S, G + 2, Hk, D)).astype(np.float32)
    out, _ = F.flash_attn_qkvpacked(paddle.to_tensor(qkv))
    q = qkv[:, :, :G].reshape(B, S, G * Hk, D)
    k, v = qkv[:, :, -2], qkv[:, :, -1]
    for j in range(G * Hk):
        kv = j // G
        ref = torch.nn.functional.scaled_dot_product_attention(
            torch.tensor(q[:, :, j]).unsqueeze(1),
            torch.tensor(k[:, :, kv]).unsqueeze(1),
            torch.tensor(v[:, :, kv]).unsqueeze(1)).squeeze(1).numpy()
        np.testing.assert_allclose(t2n(out)[:, :, j], ref, rtol=1e-4,
                                   atol=1e-5)


def test_rnnt_loss_empty_transcript(rng):
    # U=1 (label_lengths=0): loss = -sum of blank log-probs along t
    logits = rng.standard_normal((1, 3, 1, 4)).astype(np.float32)
    loss = F.rnnt_loss(paddle.to_tensor(logits),
                       paddle.to_tensor(np.zeros((1, 0), np.int32)),
                       paddle.to_tensor(np.array([3], np.int32)),
                       paddle.to_tensor(np.array([0], np.int32)),
                       blank=0, reduction="none")
    logp = np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
    exp = -logp[0, :, 0, 0].sum()
    assert abs(float(t2n(loss)[0]) - exp) < 1e-4


def test_lp_pool1d_nlc_data_format(rng):
    x = rng.standard_normal((1, 6, 3)).astype(np.float32)  # N, L, C
    out = F.lp_pool1d(paddle.to_tensor(x), 2.0, 2, 2, data_format="NLC")
    assert t2n(out).shape == (1, 3, 3)
    ref = torch.nn.functional.lp_pool1d(
        torch.tensor(x.transpose(0, 2, 1)), 2.0, 2, 2).numpy()
    np.testing.assert_allclose(t2n(out), ref.transpose(0, 2, 1), rtol=1e-5)
