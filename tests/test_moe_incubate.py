"""MoE (dense + expert-parallel) and incubate fused ops.

Reference test model: test/collective/fleet moe tests + op unit tests vs numpy
references (SURVEY.md §4). EP runs on the 8-virtual-device CPU mesh.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.distributed.models.moe import MoELayer, SwitchGate
from paddle_tpu.incubate.nn import functional as IF
from paddle_tpu.ops.kernels.moe import top_k_gating, moe_forward_dense

# Importable again since the jax<0.5 shard_map import fallback (round
# 6) un-broke collection; the file is gated behind the `slow` marker
# because tier-1 has a hard wall-time budget and at the seed this file
# contributed a collection ERROR (zero runtime). Run explicitly or
# without -m "not slow" for full coverage.
pytestmark = pytest.mark.slow



# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------

def _np_reference_moe(x, rw, wg, wu, wd, top_k, capacity):
    """Exact per-token loop reference of capacity-gated swiglu MoE."""
    t, d = x.shape
    e = rw.shape[1]
    logits = x @ rw
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    counts = np.zeros(e, int)
    y = np.zeros_like(x)
    choices = np.argsort(-probs, axis=1)[:, :top_k]
    kept_w = np.zeros((t, top_k))
    for k in range(top_k):
        for ti in range(t):
            ex = choices[ti, k]
            if counts[ex] < capacity:
                kept_w[ti, k] = probs[ti, ex]
                counts[ex] += 1
    # normalize over top_k
    denom = probs[np.arange(t)[:, None], choices].sum(1)
    for ti in range(t):
        for k in range(top_k):
            if kept_w[ti, k] > 0:
                ex = choices[ti, k]
                w = kept_w[ti, k] / max(denom[ti], 1e-9) if top_k > 1 \
                    else kept_w[ti, k]
                h = x[ti] @ wg[ex], x[ti] @ wu[ex]
                act = (h[0] / (1 + np.exp(-h[0]))) * h[1]
                y[ti] += w * (act @ wd[ex])
    return y


def test_dense_moe_matches_reference(rng):
    t, d, f, e = 32, 16, 32, 4
    x = rng.standard_normal((t, d)).astype(np.float32)
    rw = rng.standard_normal((d, e)).astype(np.float32) * 0.1
    wg = rng.standard_normal((e, d, f)).astype(np.float32) * 0.1
    wu = rng.standard_normal((e, d, f)).astype(np.float32) * 0.1
    wd = rng.standard_normal((e, f, d)).astype(np.float32) * 0.1
    capacity = t  # ample: nothing dropped, order-independent
    y, aux = moe_forward_dense(jnp.asarray(x), jnp.asarray(rw), jnp.asarray(wg),
                               jnp.asarray(wu), jnp.asarray(wd), top_k=2,
                               capacity_factor=float(capacity * e) / t)
    ref = _np_reference_moe(x, rw, wg, wu, wd, top_k=2, capacity=capacity)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-5)
    assert np.isfinite(float(aux))


def test_capacity_drops_tokens(rng):
    # all tokens prefer expert 0; capacity 1 keeps only the first
    t, e = 8, 4
    logits = jnp.asarray(np.tile([10.0, 0.0, 0.0, 0.0], (t, 1)).astype(np.float32))
    disp, comb, aux, _ = top_k_gating(logits, 1, 1)
    assert int(disp.sum()) == 1          # one slot filled
    assert float(comb[0].sum()) > 0      # first token kept
    assert float(comb[1:].sum()) == 0    # rest dropped


def test_moe_layer_ep_matches_dense(rng):
    """Expert-parallel == single-device result (ample capacity)."""
    import jax
    from jax.sharding import Mesh
    t, d, f, e = 64, 16, 32, 8
    x = paddle.to_tensor(rng.standard_normal((2, t // 2, d)).astype(np.float32))

    dense = MoELayer(d, f, e, gate="gshard", capacity_factor=float(e))
    devs = np.asarray(jax.devices()[:8], dtype=object)
    mesh = Mesh(devs, ("ep",))
    ep = MoELayer(d, f, e, gate="gshard", capacity_factor=float(e),
                  mesh=mesh, axis_name="ep")
    ep.set_state_dict(dense.state_dict())

    y_dense = dense(x)
    y_ep = ep(x)
    np.testing.assert_allclose(np.asarray(y_ep._value), np.asarray(y_dense._value),
                               rtol=2e-4, atol=2e-5)
    # EP aux loss uses per-shard batch statistics (like the reference's per-rank
    # gate loss) — same scale as the global-batch value, not identical
    assert np.isfinite(float(ep.l_aux._value))
    assert abs(float(ep.l_aux._value) - float(dense.l_aux._value)) < 1.0


def test_moe_layer_grads_flow(rng):
    d, f, e = 8, 16, 4
    layer = MoELayer(d, f, e, gate="switch", capacity_factor=4.0)
    x = paddle.to_tensor(rng.standard_normal((16, d)).astype(np.float32))
    y = layer(x)
    loss = (y * y).sum() + layer.l_aux
    loss.backward()
    assert layer.w_up.grad is not None
    assert float(np.abs(np.asarray(layer.gate.weight.grad._value)).sum()) > 0


# ---------------------------------------------------------------------------
# incubate fused functional
# ---------------------------------------------------------------------------

def test_fused_rms_norm(rng):
    x = paddle.to_tensor(rng.standard_normal((4, 32)).astype(np.float32))
    w = paddle.to_tensor(rng.standard_normal((32,)).astype(np.float32))
    out = IF.fused_rms_norm(x, w, epsilon=1e-6)
    ref = F.rms_norm(x, w, epsilon=1e-6)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref._value),
                               rtol=1e-5, atol=1e-6)


def test_fused_rms_norm_residual(rng):
    x = paddle.to_tensor(rng.standard_normal((4, 32)).astype(np.float32))
    r = paddle.to_tensor(rng.standard_normal((4, 32)).astype(np.float32))
    w = paddle.to_tensor(np.ones(32, np.float32))
    out, res = IF.fused_rms_norm(x, w, residual=r)
    np.testing.assert_allclose(np.asarray(res._value),
                               np.asarray(x._value) + np.asarray(r._value))


def test_fused_rope_matches_llama(rng):
    from paddle_tpu.models.llama import precompute_rope, apply_rope
    b, s, h, d = 2, 16, 4, 32
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    cos, sin = precompute_rope(d, s)
    ref_q, ref_k = apply_rope(jnp.asarray(q), jnp.asarray(k), cos, sin)
    out_q, out_k, _ = IF.fused_rotary_position_embedding(
        paddle.to_tensor(q), paddle.to_tensor(k),
        sin=paddle.to_tensor(np.asarray(sin)), cos=paddle.to_tensor(np.asarray(cos)))
    np.testing.assert_allclose(np.asarray(out_q._value), np.asarray(ref_q),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_k._value), np.asarray(ref_k),
                               rtol=1e-5, atol=1e-5)


def test_fused_rope_position_ids(rng):
    b, s, h, d = 1, 8, 2, 16
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    pid = np.arange(s, dtype=np.int32)[None]
    out1, _, _ = IF.fused_rotary_position_embedding(
        paddle.to_tensor(q), position_ids=paddle.to_tensor(pid))
    out2, _, _ = IF.fused_rotary_position_embedding(paddle.to_tensor(q))
    np.testing.assert_allclose(np.asarray(out1._value), np.asarray(out2._value),
                               rtol=1e-5, atol=1e-6)


def test_masked_multihead_attention_decode(rng):
    b, h, d, maxlen = 2, 2, 8, 16
    cache = np.zeros((2, b, h, maxlen, d), np.float32)
    # prefill 3 steps manually through the op
    seq = np.zeros((b,), np.int32)
    outs = []
    cache_t = paddle.to_tensor(cache)
    xs = rng.standard_normal((4, b, 3 * h * d)).astype(np.float32)
    for step in range(4):
        out, cache_t = IF.masked_multihead_attention(
            paddle.to_tensor(xs[step]), cache_t,
            sequence_lengths=paddle.to_tensor(seq + step))
        outs.append(np.asarray(out._value))
    # step 0 attends only to itself: equals v_new
    qkv0 = xs[0].reshape(b, 3, h, d)
    np.testing.assert_allclose(outs[0], qkv0[:, 2].reshape(b, h * d),
                               rtol=1e-5, atol=1e-5)
    assert cache_t.shape == [2, b, h, maxlen, d]


def test_fused_moe_functional(rng):
    t, d, f, e = 16, 8, 16, 4
    x = paddle.to_tensor(rng.standard_normal((2, t // 2, d)).astype(np.float32))
    gw = paddle.to_tensor(rng.standard_normal((d, e)).astype(np.float32) * 0.1)
    w1 = paddle.to_tensor(rng.standard_normal((e, d, 2 * f)).astype(np.float32) * 0.1)
    w2 = paddle.to_tensor(rng.standard_normal((e, f, d)).astype(np.float32) * 0.1)
    out = IF.fused_moe(x, gw, w1, w2, moe_topk=2)
    assert out.shape == [2, t // 2, d]
    assert np.isfinite(np.asarray(out._value)).all()


def test_fused_transformer_layers(rng):
    from paddle_tpu.incubate.nn import FusedMultiHeadAttention, FusedFeedForward
    attn = FusedMultiHeadAttention(32, 4, dropout_rate=0.0, attn_dropout_rate=0.0)
    ffn = FusedFeedForward(32, 64, dropout_rate=0.0)
    x = paddle.to_tensor(rng.standard_normal((2, 8, 32)).astype(np.float32))
    y = ffn(attn(x))
    assert y.shape == [2, 8, 32]
    (y * y).sum().backward()
    assert attn.qkv_weight.grad is not None


class TestNewFusedOps:
    def test_fused_dropout_add_eval(self, rng):
        x = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        y = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        out = IF.fused_dropout_add(x, y, p=0.5, training=False)
        np.testing.assert_allclose(out.numpy(), x.numpy() + y.numpy())

    def test_fused_dropout_add_train_scale(self, rng):
        paddle.seed(0)
        x = paddle.to_tensor(np.ones((512, 64), "float32"))
        y = paddle.to_tensor(np.zeros((512, 64), "float32"))
        out = IF.fused_dropout_add(x, y, p=0.5, training=True).numpy()
        # upscale_in_train: surviving entries are 1/(1-p)=2, mean stays ~1
        assert set(np.unique(out)).issubset({0.0, 2.0})
        assert abs(out.mean() - 1.0) < 0.05

    def test_fused_bias_act(self, rng):
        x = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        b = paddle.to_tensor(np.ones(8, "float32"))
        out = IF.fused_bias_act(x, b, act_method="relu")
        np.testing.assert_allclose(out.numpy(),
                                   np.maximum(x.numpy() + 1, 0))
        sw = IF.fused_bias_act(x, None, act_method="swiglu").numpy()
        a_, b_ = np.split(x.numpy(), 2, -1)
        np.testing.assert_allclose(sw, (a_ / (1 + np.exp(-a_))) * b_,
                                   rtol=1e-5)

    def test_fused_feedforward_matches_manual(self, rng):
        H, FF = 8, 16
        x = paddle.to_tensor(rng.standard_normal((4, H)).astype("float32"))
        w1 = rng.standard_normal((H, FF)).astype("float32")
        w2 = rng.standard_normal((FF, H)).astype("float32")
        out = IF.fused_feedforward(
            x, paddle.to_tensor(w1), paddle.to_tensor(w2),
            ln2_scale=paddle.to_tensor(np.ones(H, "float32")),
            ln2_bias=paddle.to_tensor(np.zeros(H, "float32")),
            dropout1_rate=0.0, dropout2_rate=0.0, activation="relu")
        h = np.maximum(x.numpy() @ w1, 0) @ w2
        o = x.numpy() + h
        ref = (o - o.mean(-1, keepdims=True)) \
            / np.sqrt(o.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_fused_mha_matches_manual(self, rng):
        import math
        B, S, Hh, D = 2, 5, 2, 4
        hidden = Hh * D
        xs = paddle.to_tensor(
            rng.standard_normal((B, S, hidden)).astype("float32"))
        wqkv = rng.standard_normal((3, Hh, D, hidden)).astype("float32")
        wo = rng.standard_normal((hidden, hidden)).astype("float32")
        out = IF.fused_multi_head_attention(
            xs, paddle.to_tensor(wqkv), paddle.to_tensor(wo),
            dropout_rate=0.0, attn_dropout_rate=0.0,
            ln_scale=paddle.to_tensor(np.ones(hidden, "float32")),
            ln_bias=paddle.to_tensor(np.zeros(hidden, "float32")))
        xv = xs.numpy()
        qkv = np.einsum("bsx,thdx->tbshd", xv, wqkv)
        q, k, v = qkv
        lg = np.einsum("bshd,bthd->bhst", q, k) / math.sqrt(D)
        pr = np.exp(lg - lg.max(-1, keepdims=True))
        pr /= pr.sum(-1, keepdims=True)
        ctx = np.einsum("bhst,bthd->bshd", pr, v).reshape(B, S, hidden)
        o = xv + ctx @ wo
        ref = (o - o.mean(-1, keepdims=True)) \
            / np.sqrt(o.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-4)

    def test_block_mha_matches_naive(self, rng):
        import math
        B, Hh, D, bs_, nb = 2, 2, 4, 4, 6
        kc = np.zeros((nb, Hh, bs_, D), "float32")
        vc = np.zeros_like(kc)
        tables = np.asarray([[0, 1, -1], [2, 3, 4]])
        lens = np.asarray([2, 5])
        hist_k = rng.standard_normal((B, 6, Hh, D)).astype("float32")
        hist_v = rng.standard_normal((B, 6, Hh, D)).astype("float32")
        for i in range(B):
            for t in range(lens[i]):
                blk, slot = tables[i][t // bs_], t % bs_
                kc[blk, :, slot] = hist_k[i, t]
                vc[blk, :, slot] = hist_v[i, t]
        qkv = rng.standard_normal((B, 3 * Hh * D)).astype("float32")
        out, kc2, vc2 = IF.block_multihead_attention(
            paddle.to_tensor(qkv), paddle.to_tensor(kc), paddle.to_tensor(vc),
            None, paddle.to_tensor(lens), None,
            block_tables=paddle.to_tensor(tables))
        q3 = qkv.reshape(B, 3, Hh, D)
        for i in range(B):
            q, kn, vn = q3[i, 0], q3[i, 1], q3[i, 2]
            ks = np.concatenate([hist_k[i, :lens[i]], kn[None]], 0)
            vs = np.concatenate([hist_v[i, :lens[i]], vn[None]], 0)
            lg = np.einsum("hd,thd->ht", q, ks) / math.sqrt(D)
            pr = np.exp(lg - lg.max(-1, keepdims=True))
            pr /= pr.sum(-1, keepdims=True)
            ref = np.einsum("ht,thd->hd", pr, vs).reshape(-1)
            np.testing.assert_allclose(out.numpy()[i], ref, rtol=1e-4,
                                       atol=1e-5)
        # new token landed in its block slot
        blk, slot = tables[0][lens[0] // bs_], lens[0] % bs_
        np.testing.assert_allclose(np.asarray(kc2._value)[blk, :, slot],
                                   q3[0, 1], rtol=1e-6)
