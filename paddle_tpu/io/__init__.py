"""paddle.io analog — Dataset/Sampler/DataLoader.

Reference: python/paddle/io/ — DataLoader (reader.py:262) with subprocess worker
iterators (dataloader/dataloader_iter.py:154/:368, shared-memory queues).
TPU-native: workers produce numpy host batches (multiprocessing pool with
prefetch); device transfer happens at jnp.asarray on first op touch, letting XLA
overlap H2D with compute. Batches are returned as numpy-backed Tensors so the
common pattern `for x, y in loader:` feeds straight into jit'd train steps.
"""
from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Iterable

import numpy as np

from ..core.tensor import Tensor
from ..core import random as _random


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                        for t in tensors]

    def __getitem__(self, idx):
        return tuple(t.numpy()[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else int(self.cum[di - 1])
        return self.datasets[di][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    """Zip map-style datasets: sample i = concatenated fields of each dataset's
    sample i (reference: io/dataloader/dataset.py ComposeDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ComposeDataset needs at least one dataset")
        n = len(self.datasets[0])
        for d in self.datasets[1:]:
            if len(d) != n:
                raise ValueError("ComposeDataset requires equal lengths")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list))
                       else [sample])
        return tuple(out)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        sizes = [int(math.floor(n * f)) for f in lengths]
        sizes[-1] += n - sum(sizes)
        lengths = sizes
    rng = np.random.default_rng(generator.initial_seed()
                                if generator is not None else None)
    perm = rng.permutation(sum(lengths)).tolist()
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[off:off + ln]))
        off += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.generator = generator

    def __iter__(self):
        n = len(self.data_source)
        seed = None if self.generator is None else self.generator.initial_seed()
        rng = np.random.default_rng(seed)
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Random permutation over a fixed index subset (reference:
    io/dataloader/sampler.py SubsetRandomSampler)."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        perm = _random.default_generator.next_seed()
        rng = np.random.default_rng(perm)
        return iter(np.asarray(self.indices)[
            rng.permutation(len(self.indices))].tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        return iter(rng.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the sample space across data-parallel ranks (reference:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic, int, float)):
        return Tensor(np.stack([np.asarray(b) for b in batch]))
    if isinstance(sample, Tensor):
        return Tensor(np.stack([b.numpy() for b in batch]))
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIter:
    """Background-thread prefetcher: overlaps host batch assembly with device compute
    (the single-process analog of the reference's worker subprocesses + shm queues)."""

    def __init__(self, gen_fn, prefetch=2):
        self._q = queue.Queue(maxsize=prefetch)
        self._done = object()
        self._exc = None

        def run():
            try:
                for item in gen_fn():
                    self._q.put(item)
            except BaseException as e:  # propagate into consumer
                self._exc = e
            finally:
                self._q.put(self._done)

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self._use_shared_memory = use_shared_memory
        self._worker_init_fn = worker_init_fn
        self._timeout = timeout
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
            self.batch_size = None
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_ds:
            raise TypeError("IterableDataset has no length")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _gen(self):
        if self._iterable_ds:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        if self.num_workers > 0:
            if self._use_shared_memory and _shm_available():
                yield from self._gen_workers()
            else:
                yield from self._gen_parallel()
            return
        for batch_idx in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in batch_idx])

    def _gen_workers(self):
        """Forked worker processes + native shared-memory ring transport.

        Reference: the multiprocess DataLoader (dataloader_iter.py:368 — worker
        subprocesses pushing batches through shared-memory queues). Workers
        collate batches and push pickled host trees through one ShmChannel
        (csrc/shm_channel.cc, MPSC with process-shared condvars); the trainer
        pops, reorders by batch id, and rehydrates numpy leaves as Tensors.
        """
        import os
        import pickle
        import time
        import traceback

        from ..core.native import ShmChannel

        batches = list(self.batch_sampler)
        total = len(batches)
        if total == 0:
            return
        nw = min(self.num_workers, total)
        name = f"/pt_dl_{os.getpid()}_{id(self)}"
        chan = ShmChannel(name, capacity=256 << 20)
        pids = []
        import warnings
        try:
            for w in range(nw):
                with warnings.catch_warnings():
                    # workers run pure numpy/pickle/libc — they never touch the
                    # (multithreaded) jax runtime, so fork is safe here
                    warnings.simplefilter("ignore", RuntimeWarning)
                    pid = os.fork()
                if pid == 0:  # worker
                    code = 0
                    try:
                        wchan = ShmChannel(name, create=False)
                        _set_worker_info(w, nw, self.dataset)
                        if self._worker_init_fn is not None:
                            self._worker_init_fn(w)
                        for b in range(w, total, nw):
                            samples = [self.dataset[i] for i in batches[b]]
                            for s in samples:
                                _assert_host_sample(s)
                            data = self.collate_fn(samples)
                            payload = pickle.dumps((b, _to_host(data)),
                                                   protocol=4)
                            wchan.push(payload)
                    except BaseException:
                        try:
                            wchan.push(pickle.dumps(
                                ("error", traceback.format_exc()), protocol=4))
                        except BaseException:
                            pass
                        code = 1
                    finally:
                        os._exit(code)
                pids.append(pid)

            deadline = (time.monotonic() + self._timeout) if self._timeout \
                else None  # timeout=0: wait forever (reference semantics)
            pending = {}
            next_id = 0
            received = 0
            while received < total:
                # bounded pops so a SIGKILLed worker is noticed instead of a
                # silent infinite wait
                try:
                    raw = chan.pop(timeout_ms=5000)
                except TimeoutError:
                    if deadline is not None and time.monotonic() > deadline:
                        raise TimeoutError(
                            f"DataLoader timed out after {self._timeout}s")
                    alive = False
                    for pid in list(pids):
                        wpid, _ = os.waitpid(pid, os.WNOHANG)
                        if wpid == 0:
                            alive = True
                        else:
                            pids.remove(pid)
                    if not alive and received < total:
                        raise RuntimeError(
                            "DataLoader workers exited without delivering all "
                            f"batches ({received}/{total})")
                    continue
                obj = pickle.loads(raw)
                if obj[0] == "error":
                    raise RuntimeError(f"DataLoader worker failed:\n{obj[1]}")
                bid, data = obj
                received += 1
                pending[bid] = data
                while next_id in pending:
                    yield _from_host(pending.pop(next_id))
                    next_id += 1
        finally:
            chan.close()
            for pid in pids:
                try:
                    os.waitpid(pid, 0)
                except ChildProcessError:
                    pass
            chan.destroy()

    def _gen_parallel(self):
        """Thread-pool sample fetch (datasets in python are usually IO/np-bound, so
        threads suffice; numpy releases the GIL for decode-heavy work)."""
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            window = []
            it = iter(self.batch_sampler)
            depth = max(2, self.prefetch_factor)
            for batch_idx in itertools.islice(it, depth):
                window.append(pool.map(self.dataset.__getitem__, batch_idx))
            for batch_idx in it:
                ready = window.pop(0)
                window.append(pool.map(self.dataset.__getitem__, batch_idx))
                yield self.collate_fn(list(ready))
            for ready in window:
                yield self.collate_fn(list(ready))

    def __iter__(self):
        if self.use_buffer_reader:
            return _PrefetchIter(self._gen, prefetch=self.prefetch_factor)
        return self._gen()


def _shm_available():
    try:
        from ..core import native
        return native.available()
    except Exception:
        return False


def _assert_host_sample(obj):
    """Forked workers must not touch device-backed values (XLA threads/locks
    don't survive fork — materializing could deadlock); raise before collate
    gets a chance to convert them."""
    import jax
    v = obj._value if isinstance(obj, Tensor) else obj
    if isinstance(v, jax.Array):
        raise RuntimeError(
            "dataset __getitem__ returned a device-backed array; forked "
            "DataLoader workers cannot touch the device — return numpy "
            "arrays, or pass use_shared_memory=False to use threads")
    if isinstance(obj, (list, tuple)):
        for item in obj:
            _assert_host_sample(item)
    elif isinstance(obj, dict):
        for item in obj.values():
            _assert_host_sample(item)


def _to_host(obj):
    """Tensor leaves -> tagged numpy for cross-process pickling."""
    if isinstance(obj, Tensor):
        return ("__pt_tensor__", obj.numpy())
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_host(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    return obj


def _from_host(obj):
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__pt_tensor__":
        return Tensor(obj[1])
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_host(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _from_host(v) for k, v in obj.items()}
    return obj


class WorkerInfo:
    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info: WorkerInfo | None = None


def _set_worker_info(wid, num_workers, dataset):
    global _worker_info
    _worker_info = WorkerInfo(wid, num_workers, dataset)


def get_worker_info():
    """Inside a worker process: (id, num_workers, dataset); None in the trainer.
    Reference: python/paddle/io/dataloader/worker.py get_worker_info."""
    return _worker_info
