// TCPStore server — the rendezvous KV that bootstraps multi-process jobs.
// Reference: paddle/phi/core/distributed/store/tcp_store.h:121 (MasterDaemon
// thread + per-connection service, wait/add/get/set semantics).
//
// Thread-per-connection is deliberate: rendezvous traffic is O(world_size)
// small messages at startup/teardown, not a throughput path, and blocking
// reads keep WAIT trivial (condition_variable with deadline).
#include "pt_native.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Op : uint8_t { kSet = 1, kGet = 2, kWait = 3, kAdd = 4, kDel = 5, kNum = 6 };

struct Value {
  uint8_t tag = 0;  // 0 opaque, 1 i64 counter
  std::string bytes;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

uint32_t load_u32(const char* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return ntohl(v);
}

void push_u32(std::string* s, uint32_t v) {
  v = htonl(v);
  s->append(reinterpret_cast<const char*>(&v), 4);
}

uint64_t ntoh64(uint64_t v) {
  const uint16_t probe = 1;
  if (*reinterpret_cast<const uint8_t*>(&probe) == 1) {  // little-endian host
    v = (static_cast<uint64_t>(ntohl(static_cast<uint32_t>(v))) << 32) |
        ntohl(static_cast<uint32_t>(v >> 32));
  }
  return v;
}

void push_u64(std::string* s, uint64_t v) {
  v = ntoh64(v);  // involutive
  s->append(reinterpret_cast<const char*>(&v), 8);
}

}  // namespace

struct pt_store_server {
  int listen_fd = -1;
  std::thread accept_thread;
  std::atomic<bool> stopping{false};

  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, Value> kv;

  std::mutex conn_mu;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;

  void Serve(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    for (;;) {
      uint8_t op;
      if (!read_full(fd, &op, 1)) break;
      char klen_buf[4];
      if (!read_full(fd, klen_buf, 4)) break;
      uint32_t klen = load_u32(klen_buf);
      if (klen > (64u << 20)) break;
      std::string key(klen, '\0');
      if (klen && !read_full(fd, key.data(), klen)) break;

      std::string reply;
      switch (op) {
        case kSet: {
          uint8_t tag;
          char vlen_buf[4];
          if (!read_full(fd, &tag, 1) || !read_full(fd, vlen_buf, 4)) goto done;
          {
            uint32_t vlen = load_u32(vlen_buf);
            if (vlen > (256u << 20)) goto done;
            std::string val(vlen, '\0');
            if (vlen && !read_full(fd, val.data(), vlen)) goto done;
            {
              std::lock_guard<std::mutex> lk(mu);
              kv[key] = Value{tag, std::move(val)};
            }
            cv.notify_all();
          }
          reply.push_back(1);
          break;
        }
        case kGet: {
          std::lock_guard<std::mutex> lk(mu);
          auto it = kv.find(key);
          reply.push_back(1);
          if (it == kv.end()) {
            reply.push_back(0);
            reply.push_back(0);
            push_u32(&reply, 0);
          } else {
            reply.push_back(1);
            reply.push_back(it->second.tag);
            push_u32(&reply, static_cast<uint32_t>(it->second.bytes.size()));
            reply += it->second.bytes;
          }
          break;
        }
        case kWait: {
          char t_buf[8];
          if (!read_full(fd, t_buf, 8)) goto done;
          {
            uint64_t bits;
            std::memcpy(&bits, t_buf, 8);
            bits = ntoh64(bits);
            double timeout_s;
            std::memcpy(&timeout_s, &bits, 8);
            auto deadline = std::chrono::steady_clock::now() +
                            std::chrono::duration_cast<
                                std::chrono::steady_clock::duration>(
                                std::chrono::duration<double>(timeout_s));
            std::unique_lock<std::mutex> lk(mu);
            bool found = cv.wait_until(lk, deadline, [&] {
              return stopping.load() || kv.count(key) > 0;
            });
            if (found && !stopping.load()) {
              const Value& v = kv[key];
              reply.push_back(1);
              reply.push_back(v.tag);
              push_u32(&reply, static_cast<uint32_t>(v.bytes.size()));
              reply += v.bytes;
            } else {
              reply.push_back(0);
              reply.push_back(0);
              push_u32(&reply, 0);
            }
          }
          break;
        }
        case kAdd: {
          char d_buf[8];
          if (!read_full(fd, d_buf, 8)) goto done;
          {
            uint64_t bits;
            std::memcpy(&bits, d_buf, 8);
            int64_t delta = static_cast<int64_t>(ntoh64(bits));
            int64_t cur = 0;
            {
              std::lock_guard<std::mutex> lk(mu);
              Value& v = kv[key];
              if (v.tag == 1 && v.bytes.size() == 8) {
                std::memcpy(&cur, v.bytes.data(), 8);
              }
              cur += delta;
              v.tag = 1;
              v.bytes.assign(reinterpret_cast<const char*>(&cur), 8);
            }
            cv.notify_all();
            reply.push_back(1);
            uint64_t out;
            std::memcpy(&out, &cur, 8);
            push_u64(&reply, out);
          }
          break;
        }
        case kDel: {
          {
            std::lock_guard<std::mutex> lk(mu);
            kv.erase(key);
          }
          cv.notify_all();
          reply.push_back(1);
          break;
        }
        case kNum: {
          std::lock_guard<std::mutex> lk(mu);
          reply.push_back(1);
          push_u64(&reply, kv.size());
          break;
        }
        default:
          goto done;
      }
      if (!write_full(fd, reply.data(), reply.size())) break;
    }
  done:
    ::close(fd);
  }

  void AcceptLoop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stopping.load()) return;
        continue;
      }
      std::lock_guard<std::mutex> lk(conn_mu);
      conn_fds.push_back(fd);
      conn_threads.emplace_back([this, fd] { Serve(fd); });
    }
  }
};

extern "C" {

pt_store_server* pt_store_server_start(const char* host, int port,
                                       int* bound_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host && *host ? host : "0.0.0.0", &addr.sin_addr) !=
      1) {
    addr.sin_addr.s_addr = INADDR_ANY;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 512) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  if (bound_port) *bound_port = ntohs(addr.sin_port);

  auto* s = new pt_store_server();
  s->listen_fd = fd;
  s->accept_thread = std::thread([s] { s->AcceptLoop(); });
  return s;
}

void pt_store_server_stop(pt_store_server* s) {
  if (!s) return;
  s->stopping.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  s->cv.notify_all();  // unblock WAITers so their threads can exit
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    // wake connection threads blocked in read(), then join them — they must
    // not outlive the server state they reference
    std::lock_guard<std::mutex> lk(s->conn_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->conn_threads) {
    if (t.joinable()) t.join();
  }
  delete s;
}

uint64_t pt_store_server_num_keys(pt_store_server* s) {
  std::lock_guard<std::mutex> lk(s->mu);
  return s->kv.size();
}

}  // extern "C"
