// ShmChannel — multi-producer/single-consumer ring buffer in POSIX shared
// memory, the transport between DataLoader worker processes and the trainer.
// Reference analog: the shared-memory queues of the multiprocess DataLoader
// (python/paddle/io/dataloader/dataloader_iter.py:368 + fluid mmap_allocator).
//
// Layout: [Header | payload ring of `capacity` bytes]. Records are
// u32 length + bytes, contiguous — a record never wraps; if it doesn't fit in
// the tail space we write a SKIP marker (0xFFFFFFFF) and continue at offset 0.
// Process-shared pthread mutex + condvars give blocking push/pop without
// spinning, surviving fork() naturally.
#include "pt_native.h"

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <new>
#include <string>

namespace {

constexpr uint32_t kSkip = 0xFFFFFFFFu;

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;
  uint64_t head;  // consumer offset into ring
  uint64_t tail;  // producer offset into ring
  uint64_t used;  // bytes occupied (records + skip markers)
  uint32_t closed;
  uint32_t magic;
};

constexpr uint32_t kMagic = 0x50545348;  // "PTSH"

timespec deadline_from_ms(int timeout_ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += static_cast<long>(timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return ts;
}

}  // namespace

struct pt_shm_channel {
  Header* h = nullptr;
  char* ring = nullptr;
  size_t map_len = 0;
  std::string name;
  bool owner = false;
};

extern "C" {

pt_shm_channel* pt_shm_create(const char* name, size_t capacity) {
  if (capacity < (1 << 12)) capacity = 1 << 12;
  size_t total = sizeof(Header) + capacity;
  ::shm_unlink(name);
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
  auto* h = new (mem) Header();
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_empty, &ca);
  pthread_cond_init(&h->not_full, &ca);
  h->capacity = capacity;
  h->head = h->tail = h->used = 0;
  h->closed = 0;
  h->magic = kMagic;

  auto* c = new pt_shm_channel();
  c->h = h;
  c->ring = static_cast<char*>(mem) + sizeof(Header);
  c->map_len = total;
  c->name = name;
  c->owner = true;
  return c;
}

pt_shm_channel* pt_shm_open(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) {
    ::munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* c = new pt_shm_channel();
  c->h = h;
  c->ring = static_cast<char*>(mem) + sizeof(Header);
  c->map_len = static_cast<size_t>(st.st_size);
  c->name = name;
  c->owner = false;
  return c;
}

static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // a worker died holding the lock; state is still consistent enough for a
    // rendezvous-style teardown — mark consistent and carry on
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

int pt_shm_push(pt_shm_channel* c, const void* data, size_t len,
                int timeout_ms) {
  Header* h = c->h;
  size_t need = 4 + len;
  if (need + 4 > h->capacity) return -3;  // can never fit
  if (lock_robust(h) != 0) return -2;
  for (;;) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    uint64_t cap = h->capacity;
    uint64_t tail = h->tail;
    uint64_t space_to_end = cap - tail;
    uint64_t free_total = cap - h->used;
    bool fits_contig = space_to_end >= need;
    // if the record can't sit contiguously at the tail we must also burn the
    // tail gap with a skip marker
    uint64_t need_total = fits_contig ? need : space_to_end + need;
    if (free_total >= need_total && (fits_contig || cap >= need)) {
      if (!fits_contig) {
        if (space_to_end >= 4) {
          uint32_t skip = kSkip;
          std::memcpy(c->ring + tail, &skip, 4);
        }
        h->used += space_to_end;
        tail = 0;
      }
      uint32_t len32 = static_cast<uint32_t>(len);
      std::memcpy(c->ring + tail, &len32, 4);
      std::memcpy(c->ring + tail + 4, data, len);
      h->tail = (tail + need) % cap;
      h->used += need;
      pthread_cond_signal(&h->not_empty);
      pthread_mutex_unlock(&h->mu);
      return 0;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&h->not_full, &h->mu);
    } else {
      timespec ts = deadline_from_ms(timeout_ms);
      rc = pthread_cond_timedwait(&h->not_full, &h->mu, &ts);
    }
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
}

int pt_shm_pop(pt_shm_channel* c, void** out, size_t* out_len, int timeout_ms) {
  Header* h = c->h;
  if (lock_robust(h) != 0) return -2;
  for (;;) {
    if (h->used > 0) {
      uint64_t cap = h->capacity;
      uint64_t head = h->head;
      uint64_t space_to_end = cap - head;
      uint32_t len32 = kSkip;
      if (space_to_end >= 4) {
        std::memcpy(&len32, c->ring + head, 4);
      }
      if (space_to_end < 4 || len32 == kSkip) {
        h->used -= space_to_end;
        h->head = 0;
        continue;
      }
      void* buf = ::malloc(len32 ? len32 : 1);
      std::memcpy(buf, c->ring + head + 4, len32);
      h->head = (head + 4 + len32) % cap;
      h->used -= 4 + len32;
      pthread_cond_broadcast(&h->not_full);
      pthread_mutex_unlock(&h->mu);
      *out = buf;
      *out_len = len32;
      return 0;
    }
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&h->not_empty, &h->mu);
    } else {
      timespec ts = deadline_from_ms(timeout_ms);
      rc = pthread_cond_timedwait(&h->not_empty, &h->mu, &ts);
    }
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
}

void pt_shm_close(pt_shm_channel* c) {
  Header* h = c->h;
  if (lock_robust(h) == 0) {
    h->closed = 1;
    pthread_cond_broadcast(&h->not_empty);
    pthread_cond_broadcast(&h->not_full);
    pthread_mutex_unlock(&h->mu);
  }
}

void pt_shm_destroy(pt_shm_channel* c) {
  if (!c) return;
  ::munmap(c->h, c->map_len);
  if (c->owner) ::shm_unlink(c->name.c_str());
  delete c;
}

size_t pt_shm_capacity(pt_shm_channel* c) { return c->h->capacity; }

void pt_buf_free(void* p) { ::free(p); }

}  // extern "C"
