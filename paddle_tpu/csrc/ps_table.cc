// Native parameter-server table node — sharded sparse embedding storage with
// in-server sparse optimizers.
//
// Reference analog: paddle/fluid/distributed/ps/ — brpc PsService
// (ps/service/brpc_ps_server.cc) fronting MemorySparseTable
// (ps/table/memory_sparse_table.cc: sharded row maps + sparse SGD/Adagrad
// accessor rules, save/load). The TPU build keeps dense training state in
// device HBM under jit; this native node serves the surviving PS use case —
// host-resident huge sparse embeddings — with the same capability set
// (lazy row init, sparse optimizers, sharded concurrency, save/load),
// implemented as a C++ socket service rather than brpc.
//
// Protocol (header ints big-endian like the TCPStore; bulk id/float arrays are
// raw host-endian — client and server are assumed same-architecture, which
// holds for every deployment this runtime targets):
//   request : u8 op | u32 nlen | table name | payload
//   CREATE(1): u32 dim | u8 opt (0 sgd, 1 adagrad, 2 adam) | u32 lr_bits(f32)
//              | u32 init_std_bits(f32) | u64 seed          -> u8 ok
//   PULL(2)  : u64 n | i64 ids[n]                           -> u8 ok | u32 dim
//              | f32 rows[n*dim]
//   PUSH(3)  : u64 n | i64 ids[n] | f32 grads[n*dim]        -> u8 ok
//   SAVE(4)  : u32 plen | path                              -> u8 ok
//   LOAD(5)  : u32 plen | path                              -> u8 ok
//   STATS(6) :                                              -> u8 ok | u64 rows
//              | u64 bytes
//   PULLNOINIT(7): like PULL but missing rows come back zero and are NOT
//              materialized (inference-time lookup).
// Error replies: u8 0 | u32 len | message.
#include "pt_native.h"

#include <arpa/inet.h>
#include <math.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum PsOp : uint8_t {
  kCreate = 1,
  kPull = 2,
  kPush = 3,
  kSave = 4,
  kLoad = 5,
  kStats = 6,
  kPullNoInit = 7,
};

bool ps_read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool ps_write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

uint32_t ps_load_u32(const char* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return ntohl(v);
}

void ps_push_u32(std::string* s, uint32_t v) {
  v = htonl(v);
  s->append(reinterpret_cast<const char*>(&v), 4);
}

uint64_t ps_swap64(uint64_t v) {
  const uint16_t probe = 1;
  if (*reinterpret_cast<const uint8_t*>(&probe) == 1) {
    v = (static_cast<uint64_t>(ntohl(static_cast<uint32_t>(v))) << 32) |
        ntohl(static_cast<uint32_t>(v >> 32));
  }
  return v;
}

void ps_push_u64(std::string* s, uint64_t v) {
  v = ps_swap64(v);
  s->append(reinterpret_cast<const char*>(&v), 8);
}

// splitmix64 — deterministic per-(seed, row, lane) init stream so a row's
// initial value is identical no matter which server/order materializes it.
uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// Box–Muller over two uniform draws from the hash stream.
void fill_normal(uint64_t seed, int64_t rid, float std_dev, float* out,
                 uint32_t dim) {
  uint64_t base = mix64(seed ^ mix64(static_cast<uint64_t>(rid)));
  for (uint32_t j = 0; j < dim; j += 2) {
    uint64_t a = mix64(base + j);
    uint64_t b = mix64(base + j + 1);
    double u1 = (static_cast<double>(a >> 11) + 1.0) / 9007199254740993.0;
    double u2 = static_cast<double>(b >> 11) / 9007199254740992.0;
    double r = sqrt(-2.0 * log(u1));
    out[j] = static_cast<float>(r * cos(2.0 * M_PI * u2)) * std_dev;
    if (j + 1 < dim) {
      out[j + 1] = static_cast<float>(r * sin(2.0 * M_PI * u2)) * std_dev;
    }
  }
}

constexpr int kNumBuckets = 64;
constexpr float kAdamB1 = 0.9f, kAdamB2 = 0.999f, kEps = 1e-8f;

struct Row {
  std::vector<float> w;
  std::vector<float> s1;  // adagrad accum / adam m
  std::vector<float> s2;  // adam v
  uint32_t t = 0;         // adam step count
};

struct Table {
  uint32_t dim = 0;
  uint8_t opt = 0;  // 0 sgd, 1 adagrad, 2 adam
  float lr = 0.01f;
  float init_std = 0.01f;
  uint64_t seed = 0;

  std::mutex bucket_mu[kNumBuckets];
  std::unordered_map<int64_t, Row> buckets[kNumBuckets];

  static int BucketOf(int64_t id) {
    return static_cast<int>(mix64(static_cast<uint64_t>(id)) %
                            kNumBuckets);
  }

  Row& Materialize(int bi, int64_t id) {
    Row& row = buckets[bi][id];
    if (row.w.empty()) {
      row.w.resize(dim);
      fill_normal(seed, id, init_std, row.w.data(), dim);
    }
    return row;
  }

  void Pull(const int64_t* ids, uint64_t n, float* out, bool materialize) {
    for (uint64_t i = 0; i < n; ++i) {
      int bi = BucketOf(ids[i]);
      std::lock_guard<std::mutex> lk(bucket_mu[bi]);
      if (materialize) {
        Row& row = Materialize(bi, ids[i]);
        std::memcpy(out + i * dim, row.w.data(), dim * sizeof(float));
      } else {
        auto it = buckets[bi].find(ids[i]);
        if (it == buckets[bi].end()) {
          std::memset(out + i * dim, 0, dim * sizeof(float));
        } else {
          std::memcpy(out + i * dim, it->second.w.data(),
                      dim * sizeof(float));
        }
      }
    }
  }

  void Push(const int64_t* ids, uint64_t n, const float* grads) {
    for (uint64_t i = 0; i < n; ++i) {
      int bi = BucketOf(ids[i]);
      const float* g = grads + i * dim;
      std::lock_guard<std::mutex> lk(bucket_mu[bi]);
      Row& row = Materialize(bi, ids[i]);
      float* w = row.w.data();
      switch (opt) {
        case 1: {  // adagrad
          if (row.s1.empty()) row.s1.assign(dim, 0.f);
          float* acc = row.s1.data();
          for (uint32_t j = 0; j < dim; ++j) {
            acc[j] += g[j] * g[j];
            w[j] -= lr * g[j] / (sqrtf(acc[j]) + 1e-10f);
          }
          break;
        }
        case 2: {  // adam with per-row step count
          if (row.s1.empty()) {
            row.s1.assign(dim, 0.f);
            row.s2.assign(dim, 0.f);
          }
          row.t += 1;
          float bc1 = 1.f - powf(kAdamB1, static_cast<float>(row.t));
          float bc2 = 1.f - powf(kAdamB2, static_cast<float>(row.t));
          float* m = row.s1.data();
          float* v = row.s2.data();
          for (uint32_t j = 0; j < dim; ++j) {
            m[j] = kAdamB1 * m[j] + (1.f - kAdamB1) * g[j];
            v[j] = kAdamB2 * v[j] + (1.f - kAdamB2) * g[j] * g[j];
            w[j] -= lr * (m[j] / bc1) / (sqrtf(v[j] / bc2) + kEps);
          }
          break;
        }
        default: {  // sgd
          for (uint32_t j = 0; j < dim; ++j) w[j] -= lr * g[j];
        }
      }
    }
  }

  // File format: u64 magic | u32 dim | u8 opt | u64 nrows, then per row:
  // i64 id | u32 t | u8 has_s1 | u8 has_s2 | f32 w[dim] [| s1[dim]][| s2[dim]]
  //
  // Single pass: rows are counted while being written (each bucket under its
  // lock), then the header's nrows placeholder is patched — a concurrent push
  // materializing rows mid-save can otherwise desync the header count from
  // the rows actually written.
  bool Save(const std::string& path) {
    FILE* f = ::fopen(path.c_str(), "wb");
    if (!f) return false;
    uint64_t magic = 0x5054505354424C31ull;  // "PTPSTBL1"
    uint64_t nrows = 0;
    bool ok = ::fwrite(&magic, 8, 1, f) == 1 &&
              ::fwrite(&dim, 4, 1, f) == 1 && ::fwrite(&opt, 1, 1, f) == 1 &&
              ::fwrite(&nrows, 8, 1, f) == 1;  // placeholder
    for (int b = 0; ok && b < kNumBuckets; ++b) {
      std::lock_guard<std::mutex> lk(bucket_mu[b]);
      for (auto& [id, row] : buckets[b]) {
        uint8_t has_s1 = !row.s1.empty(), has_s2 = !row.s2.empty();
        ok = ::fwrite(&id, 8, 1, f) == 1 && ::fwrite(&row.t, 4, 1, f) == 1 &&
             ::fwrite(&has_s1, 1, 1, f) == 1 &&
             ::fwrite(&has_s2, 1, 1, f) == 1 &&
             ::fwrite(row.w.data(), sizeof(float), dim, f) == dim;
        if (ok && has_s1)
          ok = ::fwrite(row.s1.data(), sizeof(float), dim, f) == dim;
        if (ok && has_s2)
          ok = ::fwrite(row.s2.data(), sizeof(float), dim, f) == dim;
        if (!ok) break;
        ++nrows;
      }
    }
    ok = ok && ::fseek(f, 8 + 4 + 1, SEEK_SET) == 0 &&
         ::fwrite(&nrows, 8, 1, f) == 1;
    ::fclose(f);
    return ok;
  }

  bool Load(const std::string& path) {
    FILE* f = ::fopen(path.c_str(), "rb");
    if (!f) return false;
    uint64_t magic = 0, nrows = 0;
    uint32_t fdim = 0;
    uint8_t fopt = 0;
    bool ok = ::fread(&magic, 8, 1, f) == 1 &&
              magic == 0x5054505354424C31ull && ::fread(&fdim, 4, 1, f) == 1 &&
              ::fread(&fopt, 1, 1, f) == 1 && ::fread(&nrows, 8, 1, f) == 1 &&
              fdim == dim;
    if (ok) {
      // restore REPLACES table state — rows materialized after the save must
      // not survive a load
      for (int b = 0; b < kNumBuckets; ++b) {
        std::lock_guard<std::mutex> lk(bucket_mu[b]);
        buckets[b].clear();
      }
    }
    for (uint64_t i = 0; ok && i < nrows; ++i) {
      int64_t id;
      uint32_t t;
      uint8_t has_s1, has_s2;
      ok = ::fread(&id, 8, 1, f) == 1 && ::fread(&t, 4, 1, f) == 1 &&
           ::fread(&has_s1, 1, 1, f) == 1 && ::fread(&has_s2, 1, 1, f) == 1;
      if (!ok) break;
      Row row;
      row.t = t;
      row.w.resize(dim);
      ok = ::fread(row.w.data(), sizeof(float), dim, f) == dim;
      if (ok && has_s1) {
        row.s1.resize(dim);
        ok = ::fread(row.s1.data(), sizeof(float), dim, f) == dim;
      }
      if (ok && has_s2) {
        row.s2.resize(dim);
        ok = ::fread(row.s2.data(), sizeof(float), dim, f) == dim;
      }
      if (ok) {
        int bi = BucketOf(id);
        std::lock_guard<std::mutex> lk(bucket_mu[bi]);
        buckets[bi][id] = std::move(row);
      }
    }
    ::fclose(f);
    return ok;
  }

  void Stats(uint64_t* rows, uint64_t* bytes) {
    *rows = 0;
    *bytes = 0;
    for (int b = 0; b < kNumBuckets; ++b) {
      std::lock_guard<std::mutex> lk(bucket_mu[b]);
      *rows += buckets[b].size();
      for (auto& [id, row] : buckets[b]) {
        (void)id;
        *bytes +=
            (row.w.size() + row.s1.size() + row.s2.size()) * sizeof(float) +
            sizeof(Row);
      }
    }
  }
};

}  // namespace

struct pt_ps_server {
  int listen_fd = -1;
  std::thread accept_thread;
  std::atomic<bool> stopping{false};

  std::mutex reg_mu;
  // shared_ptr: a CREATE that replaces a table must not free it while another
  // connection thread is still inside Pull/Push on the old instance.
  std::unordered_map<std::string, std::shared_ptr<Table>> tables;

  // Connection threads are detached; stop() shuts down every live fd and
  // then waits for active_conns to drain before the server is deleted (a
  // joinable-vector would grow unboundedly under connection churn).
  std::mutex conn_mu;
  std::condition_variable conn_cv;
  int active_conns = 0;
  std::unordered_map<int, bool> live_fds;  // fd -> still serving

  std::shared_ptr<Table> Find(const std::string& name) {
    std::lock_guard<std::mutex> lk(reg_mu);
    auto it = tables.find(name);
    return it == tables.end() ? nullptr : it->second;
  }

  static void ReplyErr(std::string* reply, const char* msg) {
    reply->push_back(0);
    ps_push_u32(reply, static_cast<uint32_t>(strlen(msg)));
    reply->append(msg);
  }

  void Serve(int fd) {
    // A request that throws (bad_alloc on an absurd n*dim, etc.) must drop
    // this connection, not std::terminate the host process.
    try {
      ServeLoop(fd);
    } catch (...) {
    }
    {
      std::lock_guard<std::mutex> lk(conn_mu);
      live_fds.erase(fd);  // erase BEFORE close so stop() never shuts down a
                           // reused descriptor
      --active_conns;
      conn_cv.notify_all();
    }
    ::close(fd);
  }

  void ServeLoop(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::vector<int64_t> ids;
    std::vector<float> vals;
    for (;;) {
      uint8_t op;
      if (!ps_read_full(fd, &op, 1)) break;
      char nlen_buf[4];
      if (!ps_read_full(fd, nlen_buf, 4)) break;
      uint32_t nlen = ps_load_u32(nlen_buf);
      if (nlen > (1u << 16)) break;
      std::string name(nlen, '\0');
      if (nlen && !ps_read_full(fd, name.data(), nlen)) break;

      std::string reply;
      switch (op) {
        case kCreate: {
          char buf[4 + 1 + 4 + 4 + 8];
          if (!ps_read_full(fd, buf, sizeof(buf))) goto done;
          {
            auto t = std::make_shared<Table>();
            t->dim = ps_load_u32(buf);
            t->opt = static_cast<uint8_t>(buf[4]);
            uint32_t lr_bits = ps_load_u32(buf + 5);
            uint32_t std_bits = ps_load_u32(buf + 9);
            std::memcpy(&t->lr, &lr_bits, 4);
            std::memcpy(&t->init_std, &std_bits, 4);
            uint64_t seed;
            std::memcpy(&seed, buf + 13, 8);
            t->seed = ps_swap64(seed);
            if (t->dim == 0 || t->dim > (1u << 20)) {
              ReplyErr(&reply, "bad dim");
              break;
            }
            std::lock_guard<std::mutex> lk(reg_mu);
            tables[name] = std::move(t);  // re-create replaces
          }
          reply.push_back(1);
          break;
        }
        case kPull:
        case kPullNoInit: {
          char n_buf[8];
          if (!ps_read_full(fd, n_buf, 8)) goto done;
          {
            uint64_t n;
            std::memcpy(&n, n_buf, 8);
            n = ps_swap64(n);
            if (n > (1ull << 28)) goto done;
            ids.resize(n);
            if (n && !ps_read_full(fd, ids.data(), n * 8)) goto done;
            auto t = Find(name);
            if (!t) {
              ReplyErr(&reply, "no such table");
              break;
            }
            if (n * static_cast<uint64_t>(t->dim) > (1ull << 28)) {
              ReplyErr(&reply, "pull too large");
              break;
            }
            vals.resize(n * t->dim);
            t->Pull(ids.data(), n, vals.data(), op == kPull);
            reply.push_back(1);
            ps_push_u32(&reply, t->dim);
            reply.append(reinterpret_cast<const char*>(vals.data()),
                         vals.size() * sizeof(float));
          }
          break;
        }
        case kPush: {
          // payload: u64 n, u32 grad_dim, n ids, n*grad_dim floats. The
          // explicit grad_dim lets the server DRAIN the stream even when
          // the table is unknown (or the width wrong) and reply an
          // attributable error instead of dropping the connection.
          char n_buf[12];
          if (!ps_read_full(fd, n_buf, 12)) goto done;
          {
            uint64_t n;
            std::memcpy(&n, n_buf, 8);
            n = ps_swap64(n);
            uint32_t gdim = ps_load_u32(n_buf + 8);
            if (n > (1ull << 28) || gdim == 0 || gdim > (1u << 20) ||
                n * static_cast<uint64_t>(gdim) > (1ull << 28))
              goto done;  // protocol-level bound violation: not drainable
            ids.resize(n);
            if (n && !ps_read_full(fd, ids.data(), n * 8)) goto done;
            vals.resize(n * gdim);
            if (n &&
                !ps_read_full(fd, vals.data(), vals.size() * sizeof(float)))
              goto done;
            auto t = Find(name);
            if (!t) {
              ReplyErr(&reply, "no such table");
              break;
            }
            if (t->dim != gdim) {
              ReplyErr(&reply, "push dim mismatch");
              break;
            }
            t->Push(ids.data(), n, vals.data());
            reply.push_back(1);
          }
          break;
        }
        case kSave:
        case kLoad: {
          char p_buf[4];
          if (!ps_read_full(fd, p_buf, 4)) goto done;
          {
            uint32_t plen = ps_load_u32(p_buf);
            if (plen > (1u << 16)) goto done;
            std::string path(plen, '\0');
            if (plen && !ps_read_full(fd, path.data(), plen)) goto done;
            auto t = Find(name);
            if (!t) {
              ReplyErr(&reply, "no such table");
              break;
            }
            bool ok = op == kSave ? t->Save(path) : t->Load(path);
            if (ok) {
              reply.push_back(1);
            } else {
              ReplyErr(&reply, op == kSave ? "save failed" : "load failed");
            }
          }
          break;
        }
        case kStats: {
          auto t = Find(name);
          if (!t) {
            ReplyErr(&reply, "no such table");
            break;
          }
          uint64_t rows, bytes;
          t->Stats(&rows, &bytes);
          reply.push_back(1);
          ps_push_u64(&reply, rows);
          ps_push_u64(&reply, bytes);
          break;
        }
        default:
          goto done;
      }
      if (!ps_write_full(fd, reply.data(), reply.size())) break;
    }
  done:
    return;
  }

  void AcceptLoop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stopping.load()) return;
        // back off on persistent errors (EMFILE etc.) instead of busy-spin
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
        continue;
      }
      {
        std::lock_guard<std::mutex> lk(conn_mu);
        live_fds[fd] = true;
        ++active_conns;
      }
      std::thread([this, fd] { Serve(fd); }).detach();
    }
  }
};

extern "C" {

pt_ps_server* pt_ps_server_start(const char* host, int port, int* bound_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host && *host ? host : "0.0.0.0", &addr.sin_addr) !=
      1) {
    addr.sin_addr.s_addr = INADDR_ANY;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 512) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  if (bound_port) *bound_port = ntohs(addr.sin_port);

  auto* s = new pt_ps_server();
  s->listen_fd = fd;
  s->accept_thread = std::thread([s] { s->AcceptLoop(); });
  return s;
}

void pt_ps_server_stop(pt_ps_server* s) {
  if (!s) return;
  s->stopping.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    std::unique_lock<std::mutex> lk(s->conn_mu);
    for (auto& [fd, live] : s->live_fds) {
      (void)live;
      ::shutdown(fd, SHUT_RDWR);
    }
    // wait for detached connection threads to finish with server state
    s->conn_cv.wait(lk, [s] { return s->active_conns == 0; });
  }
  delete s;
}

}  // extern "C"
