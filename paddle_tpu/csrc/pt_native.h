// Native runtime layer for paddle_tpu — C ABI consumed via ctypes.
//
// Reference analogs:
//   TCPStore        -> paddle/phi/core/distributed/store/tcp_store.h:121
//   ShmChannel      -> fluid DataLoader shared-mem queues
//                      (python/paddle/io/dataloader/dataloader_iter.py:368,
//                       paddle/fluid/memory/allocation/mmap_allocator.cc)
//   numeric scan    -> FLAGS_check_nan_inf path
//                      (phi/kernels/check_numerics_kernel.h)
//
// TPU-native rationale: device-side compute and collectives live in XLA; the
// native layer owns the HOST runtime around it — rendezvous, IO staging, and
// numeric auditing of host buffers — exactly the parts the reference implements
// in C++ because the GIL would serialize them.
#pragma once

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------------------
// TCPStore server.  Binary length-prefixed protocol (all big-endian):
//   request : u8 op | u32 klen | key bytes | op-specific payload
//   SET(1)  : u8 tag | u32 vlen | value        -> reply u8 ok
//   GET(2)  :                                  -> u8 ok | u8 has | u8 tag |
//                                                 u32 vlen | value
//   WAIT(3) : f64 timeout_sec (as u64 bits)    -> u8 ok(1)/timeout(0) | u8 tag |
//                                                 u32 vlen | value
//   ADD(4)  : i64 delta                        -> u8 ok | i64 new_value
//   DEL(5)  :                                  -> u8 ok
//   NUM(6)  : (klen==0)                        -> u8 ok | u64 num_keys
// Tags: 0 = opaque bytes (pickle), 1 = i64 counter.
// ---------------------------------------------------------------------------

typedef struct pt_store_server pt_store_server;

// Binds host:port (port 0 = ephemeral), starts accept thread. Returns NULL on
// failure. *bound_port receives the actual port.
pt_store_server* pt_store_server_start(const char* host, int port,
                                       int* bound_port);
void pt_store_server_stop(pt_store_server* s);
uint64_t pt_store_server_num_keys(pt_store_server* s);

// ---------------------------------------------------------------------------
// ShmChannel: multi-producer single-consumer ring buffer in POSIX shared
// memory, for DataLoader worker -> main-process batch transport.
// ---------------------------------------------------------------------------

typedef struct pt_shm_channel pt_shm_channel;

// create: allocates /dev/shm segment `name` with `capacity` payload bytes.
pt_shm_channel* pt_shm_create(const char* name, size_t capacity);
// open: attach to an existing segment (worker side).
pt_shm_channel* pt_shm_open(const char* name);
// push: blocks until space (timeout_ms < 0 = forever). Returns 0 ok, -1 timeout,
// -2 channel closed.
int pt_shm_push(pt_shm_channel* c, const void* data, size_t len, int timeout_ms);
// pop: blocks until a message (timeout semantics as push). On success *out is a
// malloc'd buffer the caller frees with pt_buf_free, *out_len its size.
int pt_shm_pop(pt_shm_channel* c, void** out, size_t* out_len, int timeout_ms);
// mark closed (consumers/producers wake up and see -2).
void pt_shm_close(pt_shm_channel* c);
// detach mapping (and on the creator, unlink the segment).
void pt_shm_destroy(pt_shm_channel* c);
size_t pt_shm_capacity(pt_shm_channel* c);
void pt_buf_free(void* p);

// ---------------------------------------------------------------------------
// Parameter-server table node (csrc/ps_table.cc): sharded sparse embedding
// storage with in-server sparse SGD/Adagrad/Adam, lazy deterministic row
// init, save/load. Reference analog: paddle/fluid/distributed/ps/ (brpc
// PsService + MemorySparseTable). Protocol documented at the top of
// ps_table.cc; the Python client lives in incubate/distributed/ps.py.
// ---------------------------------------------------------------------------

typedef struct pt_ps_server pt_ps_server;

pt_ps_server* pt_ps_server_start(const char* host, int port, int* bound_port);
void pt_ps_server_stop(pt_ps_server* s);

// ---------------------------------------------------------------------------
// Numeric audit: multithreaded nan/inf/absmax scan over host buffers.
// kind: 0=f32 1=f64 2=bf16 3=f16
// ---------------------------------------------------------------------------

typedef struct {
  long long nan_count;
  long long inf_count;
  long long zero_count;
  long long finite_count;
  double abs_max;
  double min;  // over finite values; +inf when none
  double max;  // over finite values; -inf when none
  double sum;  // finite values only
} pt_scan_result;

void pt_scan_floats(const void* data, size_t n, int kind, int num_threads,
                    pt_scan_result* out);

#ifdef __cplusplus
}
#endif
