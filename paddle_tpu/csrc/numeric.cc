// Numeric audit — multithreaded nan/inf/absmax/sum scan over host buffers.
// Reference analog: FLAGS_check_nan_inf -> CheckTensorHasNanOrInf
// (paddle/fluid/eager/nan_inf_utils.h:38, phi check_numerics kernel). Device
// tensors are audited inside the compiled program (jnp.isnan under jit); this
// path audits HOST staging buffers (dataloader output, checkpoints) where
// python-loop scanning would be orders of magnitude too slow.
#include "pt_native.h"

#include <cmath>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

namespace {

float bf16_to_f32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

float f16_to_f32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;
    } else {  // subnormal
      int e = -1;
      do {
        e++;
        mant <<= 1;
      } while ((mant & 0x400) == 0);
      bits = sign | ((127 - 15 - e) << 23) | ((mant & 0x3FF) << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (mant << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

template <typename Load>
void scan_chunk(const uint8_t* base, size_t elem_size, size_t begin, size_t end,
                Load load, pt_scan_result* r) {
  long long nans = 0, infs = 0, zeros = 0, finites = 0;
  double amax = 0.0, sum = 0.0;
  double vmin = std::numeric_limits<double>::infinity();
  double vmax = -std::numeric_limits<double>::infinity();
  for (size_t i = begin; i < end; ++i) {
    double v = load(base + i * elem_size);
    if (std::isnan(v)) {
      ++nans;
    } else if (std::isinf(v)) {
      ++infs;
    } else {
      ++finites;
      if (v == 0.0) ++zeros;
      double a = std::fabs(v);
      if (a > amax) amax = a;
      if (v < vmin) vmin = v;
      if (v > vmax) vmax = v;
      sum += v;
    }
  }
  r->nan_count = nans;
  r->inf_count = infs;
  r->zero_count = zeros;
  r->finite_count = finites;
  r->abs_max = amax;
  r->min = vmin;
  r->max = vmax;
  r->sum = sum;
}

}  // namespace

extern "C" void pt_scan_floats(const void* data, size_t n, int kind,
                               int num_threads, pt_scan_result* out) {
  out->nan_count = out->inf_count = 0;
  out->zero_count = out->finite_count = 0;
  out->abs_max = 0.0;
  out->min = std::numeric_limits<double>::infinity();
  out->max = -std::numeric_limits<double>::infinity();
  out->sum = 0.0;
  if (!data || n == 0) return;

  auto load_f32 = [](const uint8_t* p) {
    float f;
    std::memcpy(&f, p, 4);
    return static_cast<double>(f);
  };
  auto load_f64 = [](const uint8_t* p) {
    double d;
    std::memcpy(&d, p, 8);
    return d;
  };
  auto load_bf16 = [](const uint8_t* p) {
    uint16_t v;
    std::memcpy(&v, p, 2);
    return static_cast<double>(bf16_to_f32(v));
  };
  auto load_f16 = [](const uint8_t* p) {
    uint16_t v;
    std::memcpy(&v, p, 2);
    return static_cast<double>(f16_to_f32(v));
  };

  size_t elem = kind == 1 ? 8 : (kind == 0 ? 4 : 2);
  unsigned hw = std::thread::hardware_concurrency();
  size_t nt = num_threads > 0 ? static_cast<size_t>(num_threads)
                              : (hw ? hw : 4);
  if (n < (1 << 16)) nt = 1;
  if (nt > n) nt = 1;

  std::vector<pt_scan_result> partial(nt);
  std::vector<std::thread> threads;
  const uint8_t* base = static_cast<const uint8_t*>(data);
  size_t per = n / nt;
  for (size_t t = 0; t < nt; ++t) {
    size_t b = t * per;
    size_t e = (t == nt - 1) ? n : b + per;
    auto run = [&, b, e, t] {
      switch (kind) {
        case 0:
          scan_chunk(base, 4, b, e, load_f32, &partial[t]);
          break;
        case 1:
          scan_chunk(base, 8, b, e, load_f64, &partial[t]);
          break;
        case 2:
          scan_chunk(base, 2, b, e, load_bf16, &partial[t]);
          break;
        case 3:
          scan_chunk(base, 2, b, e, load_f16, &partial[t]);
          break;
      }
    };
    if (nt == 1) {
      run();
    } else {
      threads.emplace_back(run);
    }
  }
  for (auto& th : threads) th.join();
  for (size_t t = 0; t < nt; ++t) {
    const auto& p = partial[t];
    out->nan_count += p.nan_count;
    out->inf_count += p.inf_count;
    out->zero_count += p.zero_count;
    out->finite_count += p.finite_count;
    if (p.abs_max > out->abs_max) out->abs_max = p.abs_max;
    if (p.min < out->min) out->min = p.min;
    if (p.max > out->max) out->max = p.max;
    out->sum += p.sum;
  }
  (void)elem;
}
