"""paddle.Model — Keras-like high-level training API (reference:
python/paddle/hapi/model.py:1472, fit at :2200).

TPU-native: train/eval batches run through the fused-jit TrainStep path when the
model+loss are jit-friendly (the default), falling back to eager tape autograd
on trace failure — the analog of the reference's dynamic/static dual engine.
"""
from __future__ import annotations

import os
import warnings

import numpy as np

from ..core.tensor import Tensor, no_grad
from ..nn.layer_base import Layer
from ..metric import Metric
from .. import framework_io
from ..io import DataLoader, Dataset
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    from ..ops.creation import to_tensor
    return x if isinstance(x, Tensor) else to_tensor(x)


class Model:
    """Wraps a Layer with prepare/fit/evaluate/predict/save/load."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(f"metrics must be paddle_tpu.metric.Metric, got {m}")
        self._metrics = _to_list(metrics)
        self._train_step = None

    # ------------------------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        labs = _to_list(labels)
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) before training")
        loss = self._loss(*(outs + labs))
        if isinstance(loss, (list, tuple)):
            loss = sum(loss[1:], loss[0])
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        """One optimization step; returns [loss] (+ metric results).

        The fused-jit TrainStep path always applies the optimizer update, so
        gradient accumulation (update=False) and metric computation (which
        needs the forward outputs) route through the eager tape instead.
        """
        self.network.train()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(x) for x in _to_list(labels)]
        if not update or self._metrics or getattr(self, "_accum", 1) > 1:
            self._train_step = False
        arity = (len(inputs), len(labels))
        if self._train_step and self._train_step_arity != arity:
            self._train_step = None  # rebuild: the split is baked into loss_fn

        if self._train_step is None:
            from ..jit.api import TrainStep
            n_in = len(inputs)

            def loss_fn(net, *batch):
                outs = net(*batch[:n_in])
                return self._compute_loss(outs, list(batch[n_in:]))
            try:
                self._train_step = TrainStep(self.network, loss_fn,
                                             self._optimizer)
                self._train_step_arity = arity
            except Exception:  # pragma: no cover - fallback path
                self._train_step = False
        if self._train_step:
            try:
                loss = self._train_step(*(inputs + labels))
                return self._finish_batch(loss, inputs, labels, None)
            except Exception as e:
                warnings.warn(f"jit train step failed ({e}); falling back to eager")
                self._train_step = False
        # eager fallback
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return self._finish_batch(loss, inputs, labels, outputs)

    def _finish_batch(self, loss, inputs, labels, outputs=None):
        logs = [float(np.asarray(loss._value if isinstance(loss, Tensor) else loss))]
        for m in self._metrics:
            m.update(*m.compute(*(_to_list(outputs) + labels)))
        return logs

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(x) for x in _to_list(labels)]
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels) if self._loss else None
        for m in self._metrics:
            m.update(*m.compute(*(_to_list(outputs) + labels)))
        return [float(np.asarray(loss._value))] if loss is not None else []

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        outs = self.network(*inputs)
        return [np.asarray(o._value) for o in _to_list(outs)]

    # ------------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        if hasattr(data, "__next__"):
            # one-shot iterator: materialize so every epoch sees the batches
            return list(data)
        return data  # assume re-iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """Reference: hapi/model.py fit:2200."""
        self._accum = accumulate_grad_batches
        loader = self._make_loader(train_data, batch_size, shuffle, num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs, steps=steps,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metrics)
        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                outs = self.train_batch(ins, labs, update=update)
                logs = {"loss": outs[0]}
                for m in self._metrics:
                    for n, v in zip(_to_list(m.name()), _to_list(m.accumulate())):
                        logs[n] = v
                cbks.on_train_batch_end(step, logs)
                it += 1
                if (num_iters and it >= num_iters) or self.stop_training:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, verbose=verbose, callbacks=cbks,
                              _inner=True)
            if (num_iters and it >= num_iters) or self.stop_training:
                break
        cbks.on_train_end(logs)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None, _inner=False):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, epochs=1, steps=None, verbose=verbose,
            metrics=self._metrics)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            outs = self.eval_batch(ins, labs)
            if outs:
                losses.append(outs[0])
            cbks.on_eval_batch_end(step, {"loss": outs[0] if outs else None})
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            for n, v in zip(_to_list(m.name()), _to_list(m.accumulate())):
                logs[n] = v
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_labels=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if self._inputs is not None or self._labels is not None:
                # explicit input/label specs (reference: Model(net, inputs, labels))
                n_in = len(_to_list(self._inputs)) or (
                    len(batch) - len(_to_list(self._labels)))
                return batch[:n_in], batch[n_in:]
            if has_labels and len(batch) >= 2:
                return batch[:-1], [batch[-1]]
            return batch, []
        return [batch], []

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        """state_dict(s) under <path>.pdparams/.pdopt (reference: model.py save)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        framework_io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework_io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = framework_io.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(framework_io.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network)
