"""hapi callbacks (reference: python/paddle/hapi/callbacks.py — Callback,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-epoch console logging (reference: hapi/callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose and self.params.get("verbose", 1):
            print(f"Epoch {epoch + 1}/{self.params['epochs']}")

    def _fmt(self, logs):
        items = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                items.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple, np.ndarray)):
                items.append(f"{k}: {np.asarray(v).round(4).tolist()}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and (step + 1) % self.log_freq == 0:
            print(f"step {step + 1}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Periodic model+optimizer snapshots (reference: hapi ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference: hapi EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = (self.baseline if self.baseline is not None
                     else (np.inf if self.mode == "min" else -np.inf))
        self.model.stop_training = False

    def _better(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            save_dir = self.params.get("save_dir") if hasattr(self, "params") \
                else None
            if self.save_best_model and save_dir:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no {self.monitor} improvement "
                          f"for {self.wait} evals")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference: hapi LRScheduler callback)."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=2, save_freq=1, save_dir=None, metrics=None):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "save_dir": save_dir, "metrics": metrics or []})
    return cl


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer LR when a monitored metric plateaus (reference:
    hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        # monitor picks ONE metric stream explicitly: "eval_<key>" checks the
        # eval logs (on_eval_end), a bare key checks the train logs
        # (on_epoch_end). Streams never mix, so eval_freq > 1 and
        # train/eval key collisions cannot corrupt the plateau state.
        self.monitor = monitor
        self._eval_stream = monitor.startswith("eval_")
        self._key = monitor[5:] if self._eval_stream else monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self._best = None
        self._wait = 0
        self._cooldown_counter = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_train_begin(self, logs=None):
        # fresh state per fit() on a reused callback instance
        self._best = None
        self._wait = 0
        self._cooldown_counter = 0

    def on_eval_end(self, logs=None):
        if self._eval_stream:
            self._check(logs)

    def on_epoch_end(self, epoch, logs=None):
        if not self._eval_stream:
            self._check(logs)

    def _check(self, logs):
        logs = logs or {}
        cur = logs.get(self._key)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        # Keras-exact ordering: decrement cooldown first, then re-test it
        if self._cooldown_counter > 0:
            self._cooldown_counter -= 1
            self._wait = 0
        if self._best is None or self._better(cur, self._best):
            self._best = cur
            self._wait = 0
            return
        if self._cooldown_counter > 0:
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                old = opt.get_lr()
                new = max(old * self.factor, self.min_lr)
                if new < old:
                    try:
                        opt.set_lr(new)
                    except RuntimeError:
                        return  # LRScheduler-driven optimizer: not ours to set
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
            self._cooldown_counter = self.cooldown
            self._wait = 0


class VisualDL(Callback):
    """VisualDL scalar logging (gated: the visualdl package is not available
    in this environment; reference: hapi/callbacks.py VisualDL)."""

    def __init__(self, log_dir="./log"):
        try:
            import visualdl  # noqa: F401
        except ImportError:
            raise RuntimeError(
                "VisualDL callback requires the visualdl package, which is "
                "unavailable here; use ProgBarLogger or the profiler's chrome "
                "trace export instead") from None
        # visualdl importable but the writer bridge is not implemented — fail
        # loudly rather than silently logging nothing
        raise NotImplementedError(
            "VisualDL writer bridge is not implemented in paddle_tpu; use "
            "ProgBarLogger or profiler.export_chrome_tracing")


class WandbCallback(Callback):
    """Weights&Biases logger (reference: hapi/callbacks.py WandbCallback).
    Gated on the wandb package like the reference (and VisualDL above)."""

    def __init__(self, project=None, entity=None, name=None, dir=None,
                 mode=None, job_type=None, **kwargs):
        try:
            import wandb
            self.wandb = wandb
        except ImportError as e:
            raise RuntimeError(
                "You want to use wandb which is not installed yet install "
                "it with: pip install wandb") from e
        self._kwargs = dict(project=project, entity=entity, name=name,
                            dir=dir, mode=mode, job_type=job_type, **kwargs)
        self._run = None

    def on_train_begin(self, logs=None):
        self._run = self.wandb.init(**{k: v for k, v in self._kwargs.items()
                                       if v is not None})

    def on_epoch_end(self, epoch, logs=None):
        if self._run and logs:
            self._run.log({f"train/{k}": v for k, v in logs.items()},
                          step=epoch)

    def on_eval_end(self, logs=None):
        if self._run and logs:
            self._run.log({f"eval/{k}": v for k, v in logs.items()
                           if not isinstance(v, (list, tuple))})

    def on_train_end(self, logs=None):
        if self._run:
            self._run.finish()
