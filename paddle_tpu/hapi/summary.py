"""Model summary table (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None):
    """Print a per-layer parameter table; returns {'total_params', 'trainable_params'}."""
    rows = []
    total = 0
    trainable = 0
    for name, layer in net.named_sublayers(include_self=True):
        own = [(n, p) for n, p in layer.named_parameters(include_sublayers=False)]
        if not own:
            continue
        n_params = sum(int(np.prod(p.shape)) for _, p in own)
        total += n_params
        trainable += sum(int(np.prod(p.shape)) for _, p in own
                         if not p.stop_gradient)
        rows.append((name or layer.__class__.__name__,
                     layer.__class__.__name__, n_params))
    width = max([len(r[0]) for r in rows] + [10])
    print(f"{'Layer':<{width}}  {'Type':<24}  {'Params':>12}")
    print("-" * (width + 40))
    for name, cls, n in rows:
        print(f"{name:<{width}}  {cls:<24}  {n:>12,}")
    print("-" * (width + 40))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
