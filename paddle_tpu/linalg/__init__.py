"""paddle.linalg namespace — re-export of the linear-algebra op family.

Reference: python/paddle/linalg.py (a namespace module re-exporting tensor/linalg.py
ops). The implementations live in ops/linalg.py and lower to XLA's decomposition HLOs
(QR/SVD/Eigh/Cholesky/TriangularSolve run on the MXU where possible).
"""
from ..ops.linalg import (  # noqa: F401
    matmul, mm, bmm, mv, dot, cross, norm, vector_norm, matrix_norm, dist,
    cholesky, cholesky_solve, inverse, det, slogdet, svd, qr, eig, eigh,
    eigvals, eigvalsh, matrix_power, matrix_rank, solve, triangular_solve,
    lstsq, pinv, lu, cond, multi_dot, corrcoef, cov, householder_product,
    cholesky_inverse, vecdot, matrix_transpose, svdvals, matrix_exp, lu_unpack,
    ormqr, svd_lowrank, pca_lowrank, fp8_fp8_half_gemm_fused,
)
from ..ops.math import diagonal  # noqa: F401

inv = inverse

__all__ = [
    "matmul", "mm", "bmm", "mv", "dot", "cross", "norm", "vector_norm",
    "matrix_norm", "dist", "cholesky", "cholesky_solve", "inverse", "inv", "det",
    "slogdet", "svd", "qr", "eig", "eigh", "eigvals", "eigvalsh", "matrix_power",
    "matrix_rank", "solve", "triangular_solve", "lstsq", "pinv", "lu", "cond",
    "multi_dot", "corrcoef", "cov", "householder_product",
    "cholesky_inverse", "vecdot", "matrix_transpose", "svdvals", "matrix_exp",
    "lu_unpack", "ormqr", "svd_lowrank", "pca_lowrank",
    "fp8_fp8_half_gemm_fused", "diagonal",
]
