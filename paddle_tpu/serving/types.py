"""Request-lifecycle types for ``paddle_tpu.serving``.

Reference analog: the request objects PaddleNLP's serving stack threads
through AnalysisPredictor (SURVEY §1 layer 6c) — here shaped for an async
server: a submitted request is a handle the caller can STREAM from,
cancel, or await, while the engine thread owns every interaction with the
underlying :class:`~paddle_tpu.inference.LLMEngine`.
"""
from __future__ import annotations

import collections
import dataclasses
import enum
import threading
import time
import uuid

import numpy as np

__all__ = ["RequestState", "ServeRequest", "ServeResult", "RequestHandle",
           "ServerQueueFull", "ServerClosed", "TraceContext",
           "TRACE_HOP_KINDS"]

#: every way a trace context may arrive at (or move between) serving
#: hops — the ``via`` vocabulary :meth:`TraceContext.child` accepts.
#: "submit"/"router" name the two mint sites; the rest name the hop
#: that RE-submitted the request somewhere else: a finished prefill
#: leg's KV ship, a replica-loss failover resubmission, a supervised
#: restart's re-admission, a queue-full park + retry. The PTL008
#: analysis pass (``paddle_tpu.analysis.trace_names``) checks hop
#: literals against this tuple.
TRACE_HOP_KINDS = ("submit", "router", "kv_ship", "failover", "restart",
                   "queue_retry")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One request's distributed trace identity — the Dapper-style
    (trace_id, hop) pair that survives every hop a request can take
    across the serving fleet (replica → KV ship → replica, failover
    resubmission, supervised-restart re-admission, queue-full retry),
    so ONE id names the request everywhere it ran.

    Immutable: a hop never mutates the context it received — it mints a
    :meth:`child` whose ``parent`` is the previous hop's span id, so
    the hop chain reconstructs from any single context. Minted at
    ``ReplicaRouter.submit`` (fleet entry) or ``AsyncLLMServer.submit``
    (single-server entry) when the caller didn't supply one."""

    trace_id: str                 # 16 hex chars, fleet-unique
    hop: int = 0                  # 0 at mint; +1 per resubmission hop
    parent: str | None = None     # previous hop's span_id (None at mint)
    via: str = "submit"           # TRACE_HOP_KINDS entry that made this hop

    @property
    def span_id(self):
        """This hop's span identity — ``trace_id/hop``."""
        return f"{self.trace_id}/{self.hop}"

    @classmethod
    def mint(cls, via="submit"):
        """A fresh root context (hop 0, no parent)."""
        if via not in TRACE_HOP_KINDS:
            raise ValueError(f"unknown trace hop kind {via!r}")
        return cls(trace_id=uuid.uuid4().hex[:16], via=via)

    def child(self, via):
        """The next hop's context: same trace_id, hop+1, parented on
        this hop's span id."""
        if via not in TRACE_HOP_KINDS:
            raise ValueError(f"unknown trace hop kind {via!r}")
        return TraceContext(self.trace_id, self.hop + 1, self.span_id,
                            via)

    def to_dict(self):
        return {"trace_id": self.trace_id, "hop": self.hop,
                "parent": self.parent, "via": self.via}

    @classmethod
    def coerce(cls, obj):
        """Normalize None / TraceContext / its dict form (the shape
        that rides JSON exports and recorder timelines) to a
        TraceContext or None."""
        if obj is None or isinstance(obj, cls):
            return obj
        if isinstance(obj, dict):
            return cls(str(obj["trace_id"]), int(obj.get("hop", 0)),
                       obj.get("parent"), obj.get("via", "submit"))
        raise TypeError(f"cannot coerce {type(obj).__name__} to "
                        f"TraceContext")


class ServerQueueFull(RuntimeError):
    """Admission queue at capacity and the caller declined to wait —
    the server's backpressure signal."""


class ServerClosed(RuntimeError):
    """submit() after stop() (or on a never-started server)."""


class RequestState(enum.Enum):
    QUEUED = "queued"        # in the server admission queue
    PENDING = "pending"      # handed to the engine, waiting for a slot
    RUNNING = "running"      # admitted into an engine slot (prefilled)
    FINISHED = "finished"    # terminal: engine finish / cancel / deadline


@dataclasses.dataclass
class ServeRequest:
    """One submitted generation request (server-side record)."""
    request_id: int
    prompt_ids: np.ndarray
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_p: float = 1.0
    eos_token_id: int | None = None
    #: absolute time.monotonic() deadline; the engine thread cancels the
    #: request (freeing its slot / pool blocks) once this passes
    deadline: float | None = None
    submitted_at: float = 0.0
    #: opaque caller/router metadata (e.g. the ReplicaRouter's placement
    #: decision: replica index, policy, score, affinity tokens,
    #: routing_key). Surfaced verbatim on ServeResult.routing and
    #: stamped into the request's flight-recorder trace as a "routed"
    #: span, so per-request placement is observable in explain_tail.
    routing: dict | None = None
    #: tokens this request ALREADY streamed on a previous server/replica
    #: (failover resumption): admission prefills prompt⊕resume_tokens and
    #: the engine stitches them back in front of the continuation, so the
    #: terminal ServeResult carries the full stream while only NEW tokens
    #: stream out. They count against ``max_new_tokens`` (the ORIGINAL
    #: total budget — the engine generates the remainder).
    resume_tokens: list | None = None
    #: latency-tier pin for multi-step decode: cap the engine's
    #: ``readout_stride`` while this request is resident (1 = the host
    #: syncs every step, minimizing THIS request's inter-token latency
    #: at the batch's throughput cost). None = the engine default; inert
    #: on engines without multi-step decode.
    readout_stride: int | None = None
    #: the TENANT dimension (batched multi-LoRA): 0 = the base model,
    #: > 0 = a registered adapter id. Preserved across supervised
    #: restart re-admission and router failover resubmission, so a
    #: tenant's stream can never silently continue on the wrong weights.
    adapter_id: int = 0
    #: "generate" (token stream) or "embed" (prefill-only: the result
    #: carries the mean-pooled final hidden state, no tokens)
    kind: str = "generate"
    #: carried speculative acceptance EWMA (router failover): seeds the
    #: engine's acceptance-adaptive verify-k for this request so a
    #: low-acceptance stream resumed on a survivor replica does not
    #: restart at full-window speculation. None = let the engine learn.
    spec_ewma: float | None = None
    #: disaggregated serving: stage this request's committed KV as a
    #: shippable export entry at its finish (the router sets this on the
    #: PREFILL leg so the decode replica can import instead of
    #: re-prefilling). Inert without a paged engine.
    export_kv: bool = False
    #: the request's distributed trace identity (minted at submit when
    #: absent) — preserved verbatim across restart re-admission and
    #: carried (hop-incremented) across ship/failover/retry
    #: resubmissions, so one trace_id names the request fleet-wide
    trace_ctx: TraceContext | None = None


@dataclasses.dataclass
class ServeResult:
    """Terminal outcome of a request, with its latency record."""
    request_id: int
    token_ids: list
    finish_reason: str | None
    finished: bool = True
    ttft_s: float | None = None
    e2e_s: float = 0.0
    queue_wait_s: float | None = None
    #: the request's flight-recorder span timeline (JSON-ready dict:
    #: queued → admitted → prefill chunks → per-token gaps → finish,
    #: every span stamped with the engine StepRecord id that produced
    #: it). None unless the server was started with a flight_recorder.
    trace: dict | None = None
    #: the routing/placement metadata the request was submitted with
    #: (see ServeRequest.routing) — how THIS request got where it ran
    routing: dict | None = None
    #: prefill-only (kind="embed") result: the mean-pooled final hidden
    #: state [hidden_size] (fp32 numpy), None for generation requests
    embedding: np.ndarray | None = None
    #: the trace context this (leg of the) request ran under — the
    #: terminal hop's identity; ``trace_ctx.trace_id`` joins the result
    #: back to every other hop's recorder timeline
    trace_ctx: TraceContext | None = None


class RequestHandle:
    """Caller-side view of one in-flight request.

    * **streaming**: iterate the handle (``for tok in handle``) to receive
      token ids as the engine decodes them; iteration ends at the
      terminal state (finish/cancel/deadline).
    * **await**: :meth:`result` blocks for the terminal
      :class:`ServeResult`.
    * **cancel**: :meth:`cancel` requests cancellation; the engine thread
      frees the slot (and paged pool blocks) at the next step boundary.

    Thread-safety: the engine thread produces (tokens, state
    transitions); any caller thread may consume. One condition variable
    serializes both."""

    def __init__(self, server, req: ServeRequest):
        self._server = server
        self.request = req
        self._cond = threading.Condition()
        self._tokens = collections.deque()
        #: EVERY token ever emitted to this handle, consumed or not — the
        #: supervised-restart / failover resume record: prompt⊕emitted is
        #: exactly the state a recovered engine must continue from
        self.emitted: list = []
        self.state = RequestState.QUEUED
        self.result_obj: ServeResult | None = None
        self.cancel_requested = False
        #: set by the engine thread at slot admission / first token
        self.admitted_at: float | None = None
        self.first_token_at: float | None = None
        self.last_token_at: float | None = None
        #: first moment a FREE slot existed while this request was still
        #: waiting — admission_stall_s measures admission lag from here
        self.stall_mark: float | None = None

    @property
    def request_id(self):
        return self.request.request_id

    @property
    def done(self):
        return self.state is RequestState.FINISHED

    def full_stream(self):
        """EVERYTHING this request ever streamed, across servers: the
        failover resume prefix (tokens from a previous replica) plus
        every token emitted here. THE definition the fault-tolerance
        layer builds results and restart re-admissions from — one copy,
        or eviction and recovery silently desynchronize."""
        return list(self.request.resume_tokens or []) + list(self.emitted)

    # -- engine-thread side ---------------------------------------------
    def _emit(self, tok, t=None):
        """``t``: an explicit monotonic stamp — the server passes the
        token's AMORTIZED device-step-boundary time under multi-step
        readout so latency stats see the stride's k tokens at k distinct
        times; clamped monotonic per handle."""
        with self._cond:
            self._tokens.append(tok)
            self.emitted.append(tok)
            now = time.monotonic() if t is None else t
            if self.last_token_at is not None and now < self.last_token_at:
                now = self.last_token_at
            if self.first_token_at is None:
                self.first_token_at = now
            self.last_token_at = now
            self._cond.notify_all()

    def _finish(self, result: ServeResult):
        with self._cond:
            self.result_obj = result
            self.state = RequestState.FINISHED
            self._cond.notify_all()

    # -- caller side ----------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        with self._cond:
            while not self._tokens and not self.done:
                self._cond.wait()
            if self._tokens:
                return self._tokens.popleft()
            raise StopIteration

    def tokens(self, timeout=None):
        """Generator over the token stream with an optional PER-TOKEN
        timeout (None = wait forever; raises TimeoutError when the next
        token takes longer than ``timeout`` seconds)."""
        while True:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            with self._cond:
                while not self._tokens and not self.done:
                    remaining = None if deadline is None \
                        else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(
                            f"request {self.request_id}: no token within "
                            f"{timeout}s")
                    self._cond.wait(remaining)
                if not self._tokens and self.done:
                    return
                tok = self._tokens.popleft()
            yield tok

    def result(self, timeout=None) -> ServeResult:
        """Block until the request reaches a terminal state."""
        with self._cond:
            if not self._cond.wait_for(lambda: self.done, timeout):
                raise TimeoutError(
                    f"request {self.request_id} not finished within "
                    f"{timeout}s")
            return self.result_obj

    def cancel(self):
        """Request cancellation. Idempotent; returns immediately — the
        terminal result (finish_reason 'cancelled', with any tokens
        already generated) arrives via result()/iteration."""
        self.cancel_requested = True
        self._server._wake()
