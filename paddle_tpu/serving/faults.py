"""Deterministic fault injection + restart policy for ``paddle_tpu.serving``.

Reference analog: the reference treats failure handling as a first-class
subsystem — watchdog heartbeats with hang detection (PAPER.md §2.3 row
15, ``distributed/watchdog.py``) and failure-detection/elastic recovery
(§5.3). The serving-side mirror of that layer needs one thing the
training-side watchdog never did: **reproducible chaos**. A failover test
that monkeypatches ``step_begin`` or murders a thread exercises whatever
interleaving the scheduler felt like that run; a SCRIPTED fault schedule
("raise at engine step 4", "hang 2s at step 7", "next 3 submissions see a
full queue") produces the same crash at the same engine state every run,
so tier-1 can assert token-exact recovery instead of eyeballing a soak.

Two pieces:

* :class:`FaultInjector` — the scripted schedule. It threads through
  exactly three narrow hooks: ``LLMEngine.step_begin`` /
  ``LLMEngine.step_finish`` entry (one attribute check when detached,
  like the flight recorder) and ``AsyncLLMServer.submit``'s enqueue
  (queue-full bursts). Steps are counted ONLY while the engine has work
  (idle poll passes don't advance the schedule) AND a schedule is
  pending (the detached/no-actions fast path is one attribute check and
  doesn't count), so "step N" means the N-th working step after the
  first action was scripted. Under multi-step decode
  (``readout_stride > 1``) the counter counts STRIDES — one dispatch
  covering up to k device decode steps advances the schedule by ONE,
  because the dispatch is the unit a fault can actually land between
  (there is no host boundary inside the compiled k-step loop). A crash
  scripted at ``phase="finish"`` therefore lands with a whole stride's
  tokens still unread on the device — the recovery stitch re-decodes
  them token-exactly. Hangs sleep on an Event so the
  server watchdog can :meth:`interrupt` them — the injectable stand-in
  for "cancel the stuck device call where the runtime allows it".
* :class:`RestartPolicy` — bounds for ``AsyncLLMServer(supervise=...)``:
  how many times the serving loop may be restarted after a crash, and the
  capped exponential backoff between attempts.

Every fired fault lands in :attr:`FaultInjector.fired` (the test-side
record) and on the ``faults_injected`` telemetry counter when a server
armed the injector.
"""
from __future__ import annotations

import threading
import time

from .types import ServerQueueFull

__all__ = ["FaultInjector", "InjectedFault", "RestartPolicy"]


class InjectedFault(RuntimeError):
    """An exception raised by a scripted FaultInjector schedule — the
    chaos tests' stand-in for a device/compile/runtime failure. A plain
    RuntimeError subclass so every layer treats it exactly like a real
    crash (it must NOT be special-cased anywhere outside tests)."""


class RestartPolicy:
    """Bounds for supervised serving-loop recovery.

    ``max_restarts``: total restarts one server lifetime may consume
    before a crash becomes terminal (fails every waiter with
    ``finish_reason="server_error"``, exactly like the unsupervised
    crash path). ``backoff_s * backoff_factor**(attempt-1)``, capped at
    ``max_backoff_s``, is slept between the crash and the re-arm — a
    crash LOOP must not spin the engine thread."""

    def __init__(self, max_restarts=3, backoff_s=0.05, backoff_factor=2.0,
                 max_backoff_s=2.0):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)

    def delay(self, attempt):
        """Backoff before restart ``attempt`` (1-based)."""
        return min(self.backoff_s * self.backoff_factor ** (attempt - 1),
                   self.max_backoff_s)

    def __repr__(self):
        return (f"RestartPolicy(max_restarts={self.max_restarts}, "
                f"backoff_s={self.backoff_s}, "
                f"backoff_factor={self.backoff_factor}, "
                f"max_backoff_s={self.max_backoff_s})")


class _Action:
    __slots__ = ("kind", "step", "phase", "seconds", "interruptible",
                 "request_id", "message")

    def __init__(self, kind, step=None, phase="begin", seconds=0.0,
                 interruptible=True, request_id=None,
                 message="injected fault"):
        self.kind = kind              # "raise" | "hang" | "fail_request"
        self.step = step              # None = fire at the NEXT hook
        self.phase = phase            # "begin" | "finish"
        self.seconds = seconds
        self.interruptible = interruptible
        self.request_id = request_id
        self.message = message


class FaultInjector:
    """One scripted fault schedule (attach via
    ``AsyncLLMServer(fault_injector=...)`` or ``engine.fault_injector``).

    Schedule entries fire at most once and are consumed when they fire.
    Thread-safe: tests script from their thread, the engine thread fires,
    the watchdog interrupts, submitters hit bursts."""

    def __init__(self):
        self._lock = threading.Lock()
        self._actions: list[_Action] = []
        self._burst = 0               # pending queue-full submissions
        self._interrupt = threading.Event()
        self._telemetry = None        # armed by AsyncLLMServer.start()
        self._step = 0
        #: every fault that fired, as (kind, step, detail) — the
        #: test-side assertion record
        self.fired: list[tuple] = []
        #: True while a hang action is sleeping — the watchdog's "is the
        #: stall ours to interrupt?" check
        self.hanging = False

    # -- scripting (any thread) -----------------------------------------
    def crash_at_step(self, step, message="injected fault", phase="begin"):
        """Raise :class:`InjectedFault` at engine step ``step`` (1-based,
        counting only steps with work; ``phase="finish"`` raises at the
        readout side instead of the dispatch side)."""
        with self._lock:
            self._actions.append(_Action("raise", int(step), phase,
                                         message=message))
        return self

    def hang_at_step(self, step, seconds, phase="begin",
                     interruptible=True):
        """Block the engine thread ``seconds`` at step ``step`` — the
        stuck-compile / wedged-device simulation. ``interruptible=True``
        sleeps on an Event so :meth:`interrupt` (the server watchdog)
        can end the hang early; False sleeps hard, modeling a stall
        nothing can cancel."""
        with self._lock:
            self._actions.append(_Action(
                "hang", int(step), phase, seconds=float(seconds),
                interruptible=bool(interruptible)))
        return self

    def fail_request(self, request_id, message=None):
        """Raise when request ``request_id`` occupies an engine slot at a
        dispatch — a per-request poison pill (the whole loop crashes;
        supervision decides what survives)."""
        with self._lock:
            self._actions.append(_Action(
                "fail_request", None, "begin", request_id=request_id,
                message=message or f"injected dispatch failure for "
                                   f"request {request_id}"))
        return self

    def queue_full_burst(self, n=1):
        """The next ``n`` ``submit()`` calls see a full admission queue
        (raise :class:`ServerQueueFull`) regardless of real queue depth."""
        with self._lock:
            self._burst += int(n)
        return self

    def kill(self, message="injected replica death"):
        """Crash at the very next engine hook (begin or finish,
        whichever comes first) — the "kill replica K" form the cluster
        chaos tests use instead of ad-hoc thread murder."""
        with self._lock:
            self._actions.append(_Action("raise", None, "any",
                                         message=message))
        return self

    def interrupt(self):
        """End a currently-sleeping interruptible hang (the server
        watchdog calls this when the heartbeat goes stale)."""
        self._interrupt.set()

    @property
    def step(self):
        """Engine steps counted so far (hooks on steps with work)."""
        with self._lock:
            return self._step

    def snapshot(self):
        """JSON-ready schedule state — what fired (with its step) and
        what's still pending. Rides the black-box debug bundle so a
        chaos run's postmortem is self-describing."""
        with self._lock:
            return {"step": self._step,
                    "fired": [list(f) for f in self.fired],
                    "pending": [a.kind for a in self._actions],
                    "burst_pending": self._burst,
                    "hanging": self.hanging}

    # -- hook side -------------------------------------------------------
    def _record(self, kind, step, detail):
        self.fired.append((kind, step, detail))
        tel = self._telemetry
        if tel is not None:
            try:
                tel.inc("faults_injected")
            except KeyError:
                pass

    def _take(self, phase, step, engine):
        """Pop every action due at (phase, step) — under the lock."""
        due, keep = [], []
        for a in self._actions:
            phase_ok = a.phase in (phase, "any")
            if a.kind == "fail_request":
                hit = phase == "begin" and any(
                    s is not None and s.req.request_id == a.request_id
                    for s in engine.slots)
                (due if hit else keep).append(a)
            elif phase_ok and (a.step is None or a.step == step):
                due.append(a)
            else:
                keep.append(a)
        self._actions = keep
        return due

    def _fire(self, phase, engine, count):
        with self._lock:
            if count:
                self._step += 1
            step = self._step
            due = self._take(phase, step, engine)
        for a in due:
            if a.kind == "hang":
                self._record("hang", step, a.seconds)
                self.hanging = True
                try:
                    if a.interruptible:
                        self._interrupt.clear()
                        self._interrupt.wait(a.seconds)
                    else:
                        time.sleep(a.seconds)
                finally:
                    self.hanging = False
            else:
                detail = a.message
                self._record(a.kind, step, detail)
                raise InjectedFault(detail)

    def on_step_begin(self, engine):
        """Engine hook: entry of ``LLMEngine.step_begin`` (before the
        model dispatch lock, so a hang here never blocks OTHER replicas
        sharing the model object)."""
        if not self._actions:
            return
        self._fire("begin", engine, count=engine.has_unfinished())

    def on_step_finish(self, engine):
        """Engine hook: entry of ``LLMEngine.step_finish`` (the readout
        side — after the dispatch landed, before the host sync)."""
        if not self._actions:
            return
        self._fire("finish", engine, count=False)

    def on_submit(self, server):
        """Server hook: inside ``submit()``'s enqueue try-block, so an
        injected queue-full rides the SAME bookkeeping (rejection
        counter, timeline finish, handle cleanup) as a real full queue."""
        with self._lock:
            if self._burst <= 0:
                return
            self._burst -= 1
            step = self._step
        self._record("queue_full", step, None)
        raise ServerQueueFull("injected queue_full burst")
