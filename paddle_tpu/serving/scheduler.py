"""Admission scheduling for ``paddle_tpu.serving`` — a bounded FIFO with
blocking backpressure.

Reference analog: the reference serving stack's request queue in front of
AnalysisPredictor instances; here one queue feeds one engine thread, and
the bound IS the backpressure contract: a full queue either blocks the
submitter (`block=True`, optional timeout) or raises
:class:`~paddle_tpu.serving.types.ServerQueueFull` immediately — the
server never buffers unboundedly ahead of the engine.
"""
from __future__ import annotations

import collections
import threading
import time

from .types import ServerQueueFull

__all__ = ["AdmissionQueue"]


class AdmissionQueue:
    """Bounded thread-safe FIFO of :class:`RequestHandle`.

    Producers (submitters) block in :meth:`put` when full; the engine
    thread drains via :meth:`pop` and every pop wakes one blocked
    producer. :meth:`remove` supports cancellation/deadline expiry of a
    still-queued request in O(n) — n is bounded by ``max_size``."""

    def __init__(self, max_size=64):
        if max_size < 1:
            raise ValueError(f"max_size must be >= 1, got {max_size}")
        self.max_size = int(max_size)
        self._dq = collections.deque()
        self._cond = threading.Condition()

    def __len__(self):
        with self._cond:
            return len(self._dq)

    def put(self, handle, block=True, timeout=None, front=False):
        """Enqueue, applying backpressure. Raises ServerQueueFull when the
        queue stays at capacity (immediately if ``block=False``, after
        ``timeout`` seconds otherwise).

        ``front=True`` is the RE-ADMISSION grant: the handle joins the
        HEAD of the queue, ahead of fresh arrivals — used for failover
        resumes (streams a consumer is already reading, whose service
        was paid once on the lost replica). Backpressure still applies:
        a full queue blocks or rejects a front put like any other, so
        re-admissions cannot grow the queue past its bound."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while len(self._dq) >= self.max_size:
                if not block:
                    raise ServerQueueFull(
                        f"admission queue full ({self.max_size})")
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise ServerQueueFull(
                        f"admission queue full ({self.max_size}) after "
                        f"waiting {timeout}s")
                self._cond.wait(remaining)
            if front:
                self._dq.appendleft(handle)
            else:
                self._dq.append(handle)
            self._cond.notify_all()

    def pop(self):
        """Dequeue the oldest handle, or None when empty (never blocks —
        the engine thread must keep stepping)."""
        with self._cond:
            if not self._dq:
                return None
            h = self._dq.popleft()
            self._cond.notify_all()  # space freed: wake blocked producers
            return h

    def remove(self, handle):
        """Remove a specific queued handle (cancel/deadline). True when it
        was found and removed."""
        with self._cond:
            try:
                self._dq.remove(handle)
            except ValueError:
                return False
            self._cond.notify_all()
            return True

    def drain(self):
        """Remove and return every queued handle (server shutdown)."""
        with self._cond:
            out = list(self._dq)
            self._dq.clear()
            self._cond.notify_all()
            return out
