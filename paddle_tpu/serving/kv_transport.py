"""paddle_tpu.serving.kv_transport — bytes-on-wire KV shipping.

The disaggregated prefill/decode split (DistServe/Splitwise; PAPER.md
layer 6a) moves a finished prefill's committed KV from a prefill
replica to a decode replica instead of recomputing it. The ENGINE side
of that move is PR-13's staged-entry machinery verbatim —
``LLMEngine._export_slot_kv`` gathers with the same compiled block
gather the swap tier uses, and ``LLMEngine.import_kv`` seeds the same
swap store the fenced restore path drains — so this module only owns
what ROADMAP item 2 called "the transport": turning a staged entry into
bytes and back, and the interface a real RDMA/ICI transport would
implement.

Wire format (version-tagged, self-describing):

``serialize_entry`` flattens the entry's ``k``/``v`` pytrees with
``jax.tree_util`` and emits a JSON header (identity + per-leaf dtype/
shape table + treedef repr) followed by the raw leaf bytes,
length-prefixed. Quantized pools ride transparently: an int8/int4
``(payload, scale)`` pair is just two pytree leaves with different
dtypes, so bit-exactness on the far side is a property of the format,
not a special case. ``deserialize_entry`` rebuilds plain-numpy stacks —
exactly what ``import_kv`` validates against its pool geometry.

Transports implement :class:`KVTransport.ship`; the in-process
:class:`InProcessTransport` (loopback through real serialized bytes, so
tier-1 CPU tests cover the whole wire path) is the only one here. A
multi-host transport would subclass with an actual send/recv around the
same two functions.
"""
import json
import struct
import time

import jax
import numpy as np

__all__ = ["KVTransport", "InProcessTransport", "serialize_entry",
           "deserialize_entry", "TransportError", "MIGRATION_PHASES"]

_MAGIC = b"PTKV"
_VERSION = 1

#: the phases a prefill→decode migration decomposes into, in causal
#: order. The first three are timed inside :meth:`KVTransport.ship`
#: (returned per call); "place" is the router's decode-side resubmission
#: (``_try_place``), "stitch" the destination engine's fenced restore
#: (``_try_swap_restores`` on a shipped entry). The router books one
#: ``migration_phases[phase]`` histogram per entry, explain_tail
#: attributes migration-dominated gaps as ``kv_ship:{phase}``, and the
#: PTL008 analysis pass checks phase literals against this tuple.
MIGRATION_PHASES = ("serialize", "transport", "import", "place",
                    "stitch")


class TransportError(RuntimeError):
    """A ship failed in the transport itself (encode/decode/send). The
    router treats it like any other ship failure: fall back to
    re-prefill on the destination."""


def _tree_paths(tree):
    """Stable '/'-joined key paths for the tree's leaves — the wire
    header's leaf table is keyed by these, so a reordered or reshaped
    pytree on the far side fails loudly instead of transposing KV."""
    paths = []
    for kp, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        paths.append("/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                              for p in kp))
    return paths


def serialize_entry(entry):
    """Encode a staged export entry (``LLMEngine.export_kv``'s return
    value, or one element of ``export_prefix_blocks``) to bytes.

    Layout: ``PTKV`` magic, u32 header length, JSON header, then each
    leaf's raw bytes in header-table order. The k/v leaves are plain
    numpy (the engine materialized them before handing the entry over);
    quantized ``(payload, scale)`` leaf pairs serialize like any other
    leaves — dtype + shape ride the table, bytes ride verbatim, so the
    destination reconstructs bit-identical stacks."""
    if not entry.get("ready"):
        raise TransportError("entry not materialized (ready=False); "
                             "export_kv() materializes before handoff")
    k_bufs, k_def = jax.tree_util.tree_flatten(entry["k"])
    v_bufs, v_def = jax.tree_util.tree_flatten(entry["v"])
    # pool-derived staging buffers materialize HERE (PTL006 allowlists
    # this function: the bytes were gathered by the fence-tracked
    # export and already booked on kv_ship_out_*)
    leaves = [np.ascontiguousarray(np.asarray(k_bufs[i]))
              for i in range(len(k_bufs))]
    leaves += [np.ascontiguousarray(np.asarray(v_bufs[i]))
               for i in range(len(v_bufs))]
    tokens = entry["tokens"]
    tok_b = tokens if isinstance(tokens, bytes) \
        else np.asarray(tokens, np.int32).tobytes()
    h, parent = entry.get("hash"), entry.get("parent")
    header = {
        "v": _VERSION,
        "rid": entry.get("rid"),
        # chain hashes are raw blake2b digests — hex for the JSON header
        "hash": h.hex() if h is not None else None,
        "parent": parent.hex() if parent is not None else None,
        "adapter_id": int(entry.get("adapter_id", 0)),
        "n_blocks": int(entry["n_blocks"]),
        "block_size": int(entry["block_size"]),
        "kv_quant": entry.get("kv_quant"),
        "chain": [c.hex() for c in (entry.get("chain") or ())],
        "nbytes": int(entry["nbytes"]),
        "n_k": len(k_bufs),
        "k_def": str(k_def), "v_def": str(v_def),
        "k_paths": _tree_paths(entry["k"]),
        "v_paths": _tree_paths(entry["v"]),
        # dtype rides by NAME, not .str: extension dtypes (bfloat16,
        # float8_*) stringify as opaque void ('<V2') under .str, which
        # round-trips as np.void and fails the importer's dtype check —
        # names round-trip for both numpy-native and ml_dtypes types
        "leaves": [{"dtype": a.dtype.name, "shape": list(a.shape)}
                   for a in leaves],
        "tok_len": len(tok_b),
    }
    hb = json.dumps(header, sort_keys=True).encode()
    out = [_MAGIC, struct.pack("<I", len(hb)), hb, tok_b]
    out.extend(a.tobytes() for a in leaves)
    return b"".join(out)


def deserialize_entry(data, treedefs=None):
    """Decode ``serialize_entry``'s bytes back into a staged entry.

    The k/v pytree STRUCTURE cannot ride the wire (treedefs aren't
    portable bytes), so the caller supplies ``treedefs=(k_def, v_def)``
    from its own pool — normally via :meth:`KVTransport.ship`, which
    takes them from the destination engine. The treedef reprs in the
    header are checked against the supplied ones: a mismatch means the
    two replicas run different pool layouts and the ship must fall back.
    With ``treedefs=None`` the k/v stacks come back as flat leaf LISTS
    (enough for byte-level tests)."""
    if data[:4] != _MAGIC:
        raise TransportError("bad magic: not a PTKV payload")
    (hlen,) = struct.unpack("<I", data[4:8])
    try:
        header = json.loads(data[8:8 + hlen].decode())
    except ValueError as e:
        raise TransportError(f"corrupt header: {e}")
    if header.get("v") != _VERSION:
        raise TransportError(f"wire version {header.get('v')} != "
                             f"{_VERSION}")
    off = 8 + hlen
    tok_b = data[off:off + header["tok_len"]]
    off += header["tok_len"]
    k_bufs, v_bufs = [], []
    for i, meta in enumerate(header["leaves"]):
        dt = np.dtype(meta["dtype"])
        n = int(np.prod(meta["shape"], dtype=np.int64)) * dt.itemsize
        arr = np.frombuffer(data[off:off + n], dt).reshape(meta["shape"])
        off += n
        (k_bufs if i < header["n_k"] else v_bufs).append(arr)
    if off != len(data):
        raise TransportError("trailing bytes: payload/table mismatch")
    if treedefs is not None:
        k_def, v_def = treedefs
        if str(k_def) != header["k_def"] or str(v_def) != header["v_def"]:
            raise TransportError("pool pytree structure mismatch "
                                 "between replicas")
        k = jax.tree_util.tree_unflatten(k_def, k_bufs)
        v = jax.tree_util.tree_unflatten(v_def, v_bufs)
    else:
        k, v = k_bufs, v_bufs
    entry = {"rid": header["rid"], "adapter_id": header["adapter_id"],
             "tokens": np.frombuffer(tok_b, np.int32),
             "n_blocks": header["n_blocks"],
             "block_size": header["block_size"],
             "kv_quant": header["kv_quant"],
             "chain": [bytes.fromhex(c) for c in header["chain"]],
             "k": k, "v": v, "ready": True,
             "nbytes": header["nbytes"]}
    if header.get("hash") is not None:
        entry["hash"] = bytes.fromhex(header["hash"])
        entry["parent"] = bytes.fromhex(header["parent"])
        entry["tokens"] = tok_b      # prefix-block entries keep bytes
    return entry


def _engine_treedefs(engine):
    """The destination pool's (k_def, v_def) — what deserialization
    unflattens into. Reads structure only, never array values."""
    return (jax.tree_util.tree_structure(engine._k),
            jax.tree_util.tree_structure(engine._v))


class KVTransport:
    """Bytes-on-wire transport interface for staged KV entries.

    ``ship(entry, dst_engine)`` moves ONE staged entry to the
    destination engine and returns ``(wire_bytes, phases)`` where
    ``phases`` maps the transport-side :data:`MIGRATION_PHASES` names
    (serialize/transport/import) to seconds for THIS ship — returned
    per call, never stashed on the transport, so concurrent ships
    cannot clobber each other's timings. Implementations own the wire
    (loopback now; RDMA/ICI later keep this exact signature —
    serialize on the source, move bytes, deserialize against the
    destination's treedefs, ``dst_engine.import_kv``).
    Raise :class:`TransportError` (or return False from import) and the
    router falls back to re-prefill — shipping is an optimization, never
    a correctness dependency."""

    def ship(self, entry, dst_engine):
        raise NotImplementedError

    def ship_prefix_blocks(self, entries, dst_engine):
        """Move pull-on-miss prefix-block entries; returns
        (queued_count, wire_bytes)."""
        raise NotImplementedError


class InProcessTransport(KVTransport):
    """Loopback transport: serialize → bytes → deserialize → import.

    Runs the REAL wire encode/decode (not an object handoff), so the
    tier-1 CPU tests exercise byte-level round-tripping — including
    int8/int4 ``(payload, scale)`` leaf pairs — on every ship. Keeps
    simple counters (``ship_count``, ``ship_bytes``, ``fail_count``)
    the router folds into its snapshot, and times each ship's
    serialize / transport / import phases (seconds per
    :data:`MIGRATION_PHASES` name) into the ``phases`` dict it returns
    alongside the byte count — the router books them into its
    per-phase migration histograms and trace spans. Loopback has no
    wire, so "transport" here is the decode-side deserialization; a
    real RDMA/ICI transport would time its send/recv under the same
    key."""

    def __init__(self):
        self.ship_count = 0
        self.ship_bytes = 0
        self.fail_count = 0

    def ship(self, entry, dst_engine):
        phases = {}
        try:
            t0 = time.perf_counter()
            wire = serialize_entry(entry)
            t1 = time.perf_counter()
            phases["serialize"] = t1 - t0
            staged = deserialize_entry(wire, _engine_treedefs(dst_engine))
            t2 = time.perf_counter()
            phases["transport"] = t2 - t1
            ok = dst_engine.import_kv(staged)
            phases["import"] = time.perf_counter() - t2
        except (TransportError, KeyError, ValueError) as e:
            self.fail_count += 1
            raise TransportError(str(e))
        if not ok:
            self.fail_count += 1
            raise TransportError("destination rejected entry "
                                 "(pool geometry/validation)")
        self.ship_count += 1
        self.ship_bytes += len(wire)
        return len(wire), phases

    def ship_prefix_blocks(self, entries, dst_engine):
        total = 0
        staged = []
        for e in entries:
            wire = serialize_entry(e)
            staged.append(
                deserialize_entry(wire, _engine_treedefs(dst_engine)))
            total += len(wire)
        n = dst_engine.import_prefix_blocks(staged)
        if n:
            self.ship_count += n
            self.ship_bytes += total
        return n, total
