"""Batched multi-LoRA adapters — the multi-tenant serving subsystem.

Reference analog: the reference's unified inference front-end serves many
fine-tunes of one base model through AnalysisPredictor instances (PAPER.md
§1, layer 6c); production LLM stacks do it batched (vLLM multi-LoRA /
Punica SGMV): requests carry an ``adapter_id``, and ONE compiled step
applies a gathered per-slot low-rank delta on top of the shared base
weights, so any mix of tenants rides one dispatch.

TPU-native shape — everything static so the engine's one-compiled-program
contract survives:

* :class:`AdapterStore` — the HOST registry. An adapter is a dict of
  per-target ``(A [L, d_in, r], B [L, r, d_out])`` low-rank factors for
  llama's q/k/v/o and gate/up/down projections plus a scalar ``alpha``.
  Ranks below the store's ``rank`` zero-pad (static shapes); adapter id
  0 is reserved for the base model and never registered.
* :class:`AdapterDeviceCache` — a FIXED number of device slots holding
  stacked ``[n_slots+1, L, d_in, r]`` / ``[n_slots+1, L, r, d_out]``
  buffers per target (row 0 is all-zeros = base). Admission ``acquire``s
  the request's adapter: resident → refcount bump (hit); absent → LRU
  swap-in from the host store (miss + swap, one jitted donated
  ``.at[row].set``); every slot pinned → the admission DEFERS (the
  request stays waiting), exactly like a dry KV pool. Retirement
  ``release``s; refcount-0 slots park in an LRU so a returning tenant
  hits without a swap. The allocator is pool-invariant-audited like the
  KV block allocator (``PADDLE_TPU_POOL_CHECKS=1``).
* :func:`lora_scope` — the trace-time context the engine arms around its
  model calls: :class:`paddle_tpu.models.llama.LlamaAttention` /
  ``LlamaMLP`` consult :func:`active_lora` and add the gathered delta
  ``(x @ A[s]) @ B[s] * alpha[s]`` (fp32 accumulation) to each base
  projection, where ``s`` is the per-batch-row device slot. With no
  scope armed the model body is UNTOUCHED — an engine with no adapters
  registered passes ``lora=None`` and traces the exact pre-adapter
  program, so base serving stays bit-identical.

Correctness bar: a tenant's greedy stream is token-exact vs an offline
reference whose weights were MERGED (``W + A @ B * alpha``,
:func:`apply_merged`) — and adapter identity survives preemption
re-prefill, supervised restart re-admission, and router failover, because
``adapter_id`` rides :class:`~paddle_tpu.inference.GenerationRequest`
through every one of those paths and the prefix cache chains its hashes
from a per-tenant root (no cross-tenant KV block sharing, ever).
"""
from __future__ import annotations

import os
import threading

import numpy as np

from ..analysis import lock_watchdog as _lockwatch
from ..models.lora import (LORA_TARGETS, active_lora, lora_scope,
                           lora_target_dims as _target_dims)

__all__ = ["AdapterStore", "AdapterDeviceCache", "LORA_TARGETS",
           "lora_scope", "active_lora", "apply_merged",
           "random_lora_weights"]


class AdapterStore:
    """Host-side adapter registry for ONE base-model geometry.

    ``rank`` is the store's static rank: every registered adapter's
    factors zero-pad up to it (the device stacks are shaped once).
    Adapters may target any subset of :data:`LORA_TARGETS`; untargeted
    projections stay zero (= base). Registration is allowed at any time
    — an engine picks a new adapter up at that request's admission (the
    jitted step retraces once when the FIRST adapter arrives, because
    the program gains the gather; never again after that).

    Thread-safe for the serving shape: registrations and engine-side
    reads hold one lock."""

    def __init__(self, config, rank=8):
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.config = config
        self.rank = int(rank)
        self.n_layers = int(config.num_hidden_layers)
        self.dims = _target_dims(config)
        # PADDLE_TPU_LOCK_CHECKS=1: acquisition edges feed the PTL004
        # lock-order watchdog (paddle_tpu.analysis.lock_watchdog)
        self._lock = _lockwatch.tracked(threading.Lock(),
                                        "AdapterStore._lock")
        #: adapter_id -> {"weights": {target: (A, B)}, "alpha": float}
        self._adapters = {}
        self._next_id = 1

    def __len__(self):
        with self._lock:
            return len(self._adapters)

    def ids(self):
        with self._lock:
            return sorted(self._adapters)

    def has(self, adapter_id):
        if adapter_id == 0:
            return True          # base model, always servable
        with self._lock:
            return adapter_id in self._adapters

    def register(self, weights, alpha=1.0, adapter_id=None):
        """Register one adapter; returns its id (> 0).

        ``weights``: dict target -> (A, B) with A ``[L, d_in, r]`` and B
        ``[L, r, d_out]`` (r <= the store rank; zero-padded up). A 2-D
        ``[d_in, r]`` factor broadcasts to every layer."""
        entry = {}
        for target, (A, B) in weights.items():
            if target not in self.dims:
                raise ValueError(
                    f"unknown LoRA target {target!r} (valid: "
                    f"{sorted(self.dims)})")
            d_in, d_out = self.dims[target]
            A = np.asarray(A, np.float32)
            B = np.asarray(B, np.float32)
            if A.ndim == 2:
                A = np.broadcast_to(A, (self.n_layers,) + A.shape)
            if B.ndim == 2:
                B = np.broadcast_to(B, (self.n_layers,) + B.shape)
            r = A.shape[-1]
            if r > self.rank:
                raise ValueError(
                    f"{target}: adapter rank {r} exceeds the store rank "
                    f"{self.rank} (the device stacks are shaped once)")
            if A.shape != (self.n_layers, d_in, r) or \
                    B.shape != (self.n_layers, r, d_out):
                raise ValueError(
                    f"{target}: expected A [L={self.n_layers}, {d_in}, r] "
                    f"and B [L, r, {d_out}], got {A.shape} / {B.shape}")
            if r < self.rank:           # zero-pad to the static rank
                A = np.concatenate(
                    [A, np.zeros((self.n_layers, d_in, self.rank - r),
                                 np.float32)], axis=-1)
                B = np.concatenate(
                    [B, np.zeros((self.n_layers, self.rank - r, d_out),
                                 np.float32)], axis=1)
            entry[target] = (np.ascontiguousarray(A),
                             np.ascontiguousarray(B))
        with self._lock:
            aid = self._next_id if adapter_id is None else int(adapter_id)
            if aid <= 0:
                raise ValueError("adapter_id 0 is reserved for the base "
                                 "model (ids must be > 0)")
            if aid in self._adapters:
                raise ValueError(f"duplicate adapter_id {aid}")
            self._next_id = max(self._next_id, aid) + 1
            self._adapters[aid] = {"weights": entry, "alpha": float(alpha)}
            return aid

    def get(self, adapter_id):
        with self._lock:
            return self._adapters[adapter_id]


class AdapterDeviceCache:
    """Fixed-size device cache of adapter slots over one AdapterStore.

    ``n_slots`` swappable slots; device row 0 is the always-resident
    all-zeros BASE row, so the stacked buffers have ``n_slots + 1``
    rows. ``acquire(adapter_id)`` returns the device ROW to gather in
    the fused step (0 for base), or None when every slot is pinned by a
    resident request (the caller defers admission). ``release`` drops
    one reference; a refcount-0 slot parks in an LRU (still loaded — a
    returning tenant hits without a swap) until a miss evicts it.

    ``make_zeros(shape, dtype)`` abstracts buffer creation so the engine
    can hand its mesh-aware allocator in (stacks are replicated under
    TP — the delta is computed replicated and added to the sharded base
    projection, which GSPMD reconciles)."""

    def __init__(self, store, n_slots=4, make_zeros=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.store = store
        self.n_slots = int(n_slots)
        mk = make_zeros or (lambda shape, dt: np.zeros(shape, dt))
        L, r = store.n_layers, store.rank
        S = self.n_slots + 1
        #: stacked device factors, row 0 zeros (base)
        self.A = {t: mk((S, L, d_in, r), np.float32)
                  for t, (d_in, _) in store.dims.items()}
        self.B = {t: mk((S, L, r, d_out), np.float32)
                  for t, (_, d_out) in store.dims.items()}
        self.alpha = mk((S,), np.float32)
        # ---- host allocator state -----------------------------------
        import collections
        self._slot_of = {}                       # adapter_id -> slot (0-based)
        self._slot_aid = [None] * self.n_slots   # slot -> adapter_id
        self._ref = [0] * self.n_slots
        self._free = list(range(self.n_slots))
        self._lru = collections.OrderedDict()    # loaded refcount-0 slots
        self._set_fn = None
        self.stats = {"hits": 0, "misses": 0, "swaps": 0}
        #: zero factors for UNTARGETED projections, built once — a
        #: swap-in of a sparse adapter must not re-allocate full-size
        #: zero arrays for every projection it doesn't touch
        self._zeros = {
            t: (np.zeros((L, d_in, r), np.float32),
                np.zeros((L, r, d_out), np.float32))
            for t, (d_in, d_out) in store.dims.items()}
        self._debug = os.environ.get(
            "PADDLE_TPU_POOL_CHECKS", "0") not in ("", "0")

    # -- device upload --------------------------------------------------
    def _upload(self, slot, adapter):
        """Write one adapter's factors into device row ``slot + 1`` —
        one jitted donated program (row index traced: swapping a
        different slot never recompiles)."""
        import jax
        import jax.numpy as jnp

        if self._set_fn is None:
            def set_row(As, Bs, alpha, hostA, hostB, host_alpha, row):
                As = {t: a.at[row].set(hostA[t]) for t, a in As.items()}
                Bs = {t: b.at[row].set(hostB[t]) for t, b in Bs.items()}
                alpha = alpha.at[row].set(host_alpha)
                return As, Bs, alpha
            self._set_fn = jax.jit(set_row, donate_argnums=(0, 1, 2))
        w = adapter["weights"]
        hostA, hostB = {}, {}
        for t in self.store.dims:
            if t in w:
                hostA[t], hostB[t] = w[t]
            else:            # untargeted projection: shared zero delta
                hostA[t], hostB[t] = self._zeros[t]
        self.A, self.B, self.alpha = self._set_fn(
            self.A, self.B, self.alpha, hostA, hostB,
            jnp.float32(adapter["alpha"]), jnp.int32(slot + 1))

    # -- allocator ------------------------------------------------------
    def acquire(self, adapter_id):
        """Pin ``adapter_id`` resident; returns its device ROW (0 =
        base), or None when the cache is full of pinned slots (caller
        defers the admission until a release frees one)."""
        if adapter_id == 0:
            return 0
        slot = self._slot_of.get(adapter_id)
        if slot is not None:
            if self._ref[slot] == 0:
                self._lru.pop(slot, None)
            self._ref[slot] += 1
            self.stats["hits"] += 1
            self._check_invariants()
            return slot + 1
        # miss: free slot first, else evict the LRU-oldest loaded slot.
        # A full-of-pinned-slots cache defers WITHOUT counting a miss —
        # the caller retries every step, and one deferred admission must
        # not inflate the miss counter by its wait length.
        if self._free:
            slot = self._free.pop(0)
        elif self._lru:
            slot, _ = self._lru.popitem(last=False)
            del self._slot_of[self._slot_aid[slot]]
        else:
            return None                 # every slot pinned: defer
        self.stats["misses"] += 1
        self._upload(slot, self.store.get(adapter_id))
        self.stats["swaps"] += 1
        self._slot_of[adapter_id] = slot
        self._slot_aid[slot] = adapter_id
        self._ref[slot] = 1
        self._check_invariants()
        return slot + 1

    def release(self, adapter_id):
        if adapter_id == 0:
            return
        slot = self._slot_of.get(adapter_id)
        if slot is None:
            return
        self._ref[slot] = max(0, self._ref[slot] - 1)
        if self._ref[slot] == 0:
            self._lru[slot] = None      # loaded, evictable, probe-able
        self._check_invariants()

    def resident(self, adapter_id):
        """READ-ONLY: is ``adapter_id`` currently loaded (pinned or
        LRU-parked)? The replica router's adapter-affinity probe — dict
        reads only, safe from any thread."""
        return adapter_id == 0 or adapter_id in self._slot_of

    def occupancy(self):
        """Loaded fraction of the swappable slots (pinned + LRU)."""
        return len(self._slot_of) / self.n_slots

    def _check_invariants(self):
        """Debug audit (PADDLE_TPU_POOL_CHECKS=1, armed suite-wide by
        tests/conftest.py): every slot is exactly one of {free, LRU,
        pinned}, the id<->slot maps mirror, and LRU slots are loaded
        refcount-0."""
        if not self._debug:
            return
        free, lru = set(self._free), set(self._lru)
        pinned = {s for s in range(self.n_slots)
                  if self._ref[s] > 0}
        assert not (free & lru) and not (free & pinned) \
            and not (lru & pinned), "adapter slot in two pools"
        assert free | lru | pinned == set(range(self.n_slots)), \
            "adapter slot leak"
        for s in lru:
            assert self._ref[s] == 0 and self._slot_aid[s] is not None, \
                f"LRU slot {s} pinned or empty"
        for s in free:
            assert self._slot_aid[s] is None, f"free slot {s} still mapped"
        for aid, s in self._slot_of.items():
            assert self._slot_aid[s] == aid, "slot map drift"
        assert sum(v is not None for v in self._slot_aid) == \
            len(self._slot_of), "slot_aid / slot_of size drift"


# ---------------------------------------------------------------------------
# offline merged-weights reference
# ---------------------------------------------------------------------------

def apply_merged(model, store, adapter_id):
    """Merge adapter ``adapter_id`` INTO ``model``'s weights in place
    (``W += A[l] @ B[l] * alpha`` per target per layer) — the offline
    single-tenant reference the batched path must match token-exactly.
    Returns ``model``."""
    import jax.numpy as jnp

    entry = store.get(adapter_id)
    alpha = entry["alpha"]
    for target, sub in LORA_TARGETS:
        if target not in entry["weights"]:
            continue
        A, B = entry["weights"][target]
        for li, layer in enumerate(model.llama.layers):
            lin = getattr(getattr(layer, sub), target)
            delta = (A[li] @ B[li]) * alpha          # [d_in, d_out]
            w = lin.weight
            w._value = (w._value.astype(jnp.float32)
                        + jnp.asarray(delta)).astype(w.dtype)
    return model


def random_lora_weights(config, rank, seed=0, scale=0.02, targets=None):
    """Small random (A, B) factors for every (or the given) target —
    the test/bench/example adapter generator. ``scale`` keeps the delta
    small enough that greedy decoding stays numerically stable while
    still changing the stream."""
    rng = np.random.default_rng(seed)
    dims = _target_dims(config)
    L = config.num_hidden_layers
    out = {}
    for t in (targets or dims):
        d_in, d_out = dims[t]
        out[t] = (
            rng.standard_normal((L, d_in, rank)).astype(np.float32) * scale,
            rng.standard_normal((L, rank, d_out)).astype(np.float32)
            * scale)
    return out
