"""paddle_tpu.serving — async serving subsystem over the LLM engine.

Reference analog: the serving path the reference builds from
AnalysisPredictor + PaddleNLP's masked-MHA serving stack (SURVEY §1 layer
6c). TPU-native shape: one background engine thread runs a **pipelined**
continuous-batching loop over :class:`paddle_tpu.inference.LLMEngine`
(step N+1 dispatched before step N's token sync — JAX async dispatch
overlaps device compute with host readout), in front of a bounded
admission queue with backpressure, per-request streaming/cancellation/
deadlines, and per-stage telemetry
(:mod:`paddle_tpu.profiler.serving_telemetry`).

Entry points: :class:`AsyncLLMServer` (one engine), the multichip
layer in :mod:`paddle_tpu.serving.cluster` — :func:`tp_engine` (tensor-
parallel engine whose KV pools shard across a ``("tp",)`` mesh) and
:class:`ReplicaRouter` (load- and prefix-affinity-aware placement over N
server replicas, with drain/failover) — and the fault-tolerance layer in
:mod:`paddle_tpu.serving.faults` — :class:`RestartPolicy` (supervised
engine restart with token-exact resumption) and :class:`FaultInjector`
(deterministic scripted chaos for the tier-1 recovery tests).

The SLO sensor layer rides the same server:
``AsyncLLMServer(metrics_store=True, slos=[SLO(...)])`` feeds every
gauge/counter into an in-process metric time-series store
(:mod:`paddle_tpu.profiler.metrics_store`), keeps the latency
histograms per tenant, evaluates declarative SLOs with multi-window
burn-rate alerts and arms live pathology detectors over the flight
recorder (:mod:`paddle_tpu.profiler.slo`); ``server.slo_report()`` /
``ReplicaRouter.slo_report()`` surface the per-server and fleet views.

Multi-tenant serving lives in :mod:`paddle_tpu.serving.adapters`
(:class:`AdapterStore` + the engine's batched multi-LoRA device cache —
one fused step serves any mix of fine-tunes of one base model) and
:mod:`paddle_tpu.serving.embedding` (:class:`BertEmbedEngine`, the
embed-only encoder engine behind the same server front; llama
prefill-only embeddings go through ``AsyncLLMServer.submit_embed``).
"""
from .types import (RequestHandle, RequestState, ServeRequest, ServeResult,
                    ServerClosed, ServerQueueFull)
from .scheduler import AdmissionQueue
from .faults import FaultInjector, InjectedFault, RestartPolicy
from .adapters import (AdapterDeviceCache, AdapterStore, apply_merged,
                       random_lora_weights)
from .server import AsyncLLMServer
from .embedding import BertEmbedEngine
from .cluster import (ReplicaRouter, RouterHandle, shard_model_tp,
                      tp_engine, tp_serving_mesh)
from .kv_transport import (InProcessTransport, KVTransport, TransportError,
                           deserialize_entry, serialize_entry)

__all__ = ["AsyncLLMServer", "AdmissionQueue", "RequestHandle",
           "RequestState", "ServeRequest", "ServeResult", "ServerClosed",
           "ServerQueueFull", "ReplicaRouter", "RouterHandle",
           "FaultInjector", "InjectedFault", "RestartPolicy",
           "AdapterStore", "AdapterDeviceCache", "apply_merged",
           "random_lora_weights", "BertEmbedEngine",
           "shard_model_tp", "tp_engine", "tp_serving_mesh",
           "KVTransport", "InProcessTransport", "TransportError",
           "serialize_entry", "deserialize_entry"]
