"""AsyncLLMServer — the production-shaped serving loop over LLMEngine.

Reference analog: the reference's real server is AnalysisPredictor driven
by PaddleNLP's serving stack (SURVEY §1 layer 6c) — request queue in
front, predictor loop behind, per-request streaming out. This module is
that shape on the TPU-native engine, built around the one property the
synchronous ``bench.py`` loop never exploited: **JAX async dispatch**.

The engine thread runs a PIPELINED loop::

    dispatch step N+1  ──►  device works on N+1
    sync step N's [B] token vector (device→host)   ← overlapped with N+1
    emit tokens / retire / admit (prefill dispatches are async too)

so the host-side readout + request bookkeeping of step N hides under the
device compute of step N+1 (``LLMEngine.step_begin``/``step_finish``;
buffers are donated between steps, the only per-step transfer stays the
sampled-token vector). The paged engine's host block allocator needs each
step's lens before the next dispatch, so it runs the same loop at depth 1.

On top of the loop sit the two serving layers the engine itself does not
provide:

* **request lifecycle** — bounded admission queue with backpressure
  (:class:`~paddle_tpu.serving.scheduler.AdmissionQueue`), per-request
  streaming iterators (:class:`~paddle_tpu.serving.types.RequestHandle`),
  cancellation, and per-request deadlines that free the slot / pool
  blocks at the next step boundary.
* **per-stage telemetry**
  (:class:`~paddle_tpu.profiler.serving_telemetry.ServingTelemetry`) —
  every second of engine-thread wall time lands in a named stage
  (queue_admit / prefill_dispatch / schedule / decode_dispatch /
  host_sync / emit / idle / other), plus TTFT, inter-token, e2e and
  queue-wait histograms, exported as a JSON snapshot and a
  Prometheus-style text dump.
"""
from __future__ import annotations

import collections
import os
import threading
import time

import numpy as np

from ..analysis import lock_watchdog as _lockwatch
from ..inference.llm_engine import PoolCapacityError
from ..profiler.serving_telemetry import ServingTelemetry
from .scheduler import AdmissionQueue
from .types import (RequestHandle, RequestState, ServeRequest, ServeResult,
                    ServerClosed, TraceContext)

__all__ = ["AsyncLLMServer"]


class AsyncLLMServer:
    """Async serving facade over one :class:`LLMEngine`.

    The server OWNS the engine once started: all engine calls happen on
    the background engine thread; callers interact only through
    :meth:`submit` handles. ``pipeline_depth`` None = auto (2 for the
    dense/speculative engines, 1 for paged — see module docstring).

    Usage::

        server = AsyncLLMServer(engine, max_queue_size=64)
        server.start()
        handle = server.submit(prompt_ids, max_new_tokens=64,
                               deadline_s=30.0)
        for tok in handle:          # streams as the engine decodes
            ...
        result = handle.result()    # ServeResult(finish_reason=...)
        server.stop()
    """

    def __init__(self, engine, max_queue_size=64, pipeline_depth=None,
                 poll_interval_s=0.005, telemetry=None,
                 flight_recorder=None, replica=None, supervise=None,
                 step_timeout_s=None, fault_injector=None,
                 shed_deadlines=False, metrics_store=None, slos=None,
                 pathology_detectors=None, metrics_interval_s=0.05,
                 slo_interval_s=0.25, black_box=None,
                 trace_context=True):
        """``flight_recorder``: a
        :class:`~paddle_tpu.profiler.flight_recorder.FlightRecorder`
        instance (or ``True`` for a default-sized one) to attach to the
        engine for the server's lifetime — per-step StepRecords,
        per-request span timelines, chrome-trace export and
        ``explain_tail``. None (the default) records nothing and costs
        one attribute check per step.

        ``replica``: this server's index in a multi-replica cluster
        (:class:`~paddle_tpu.serving.cluster.ReplicaRouter`). Stamped as
        a ``replica`` label on every Prometheus metric line and as the
        process lane of chrome-trace exports, so N replicas' scrapes and
        merged traces never collide. None = single-server (unlabeled).

        ``supervise``: a :class:`~paddle_tpu.serving.RestartPolicy` arms
        SUPERVISED recovery — a serving-loop crash snapshots every
        in-flight request (prompt + tokens already streamed), resets the
        engine (pool/allocator/prefix-store rebuilt, invariants clean),
        and re-admits each one as prompt⊕streamed-tokens so its stream
        CONTINUES token-exactly (greedy always; sampled via the per-
        (request, position) fold_in sampling keys). Restarts are bounded
        with exponential backoff; an exhausted policy fails every waiter
        with ``finish_reason="server_error"`` carrying the partial
        tokens, exactly like the unsupervised (None, default) path.

        ``step_timeout_s``: arms the WATCHDOG — the loop stamps a
        heartbeat every pass (one monotonic read); a watchdog thread
        flips the ``server_healthy`` gauge to 0 (and :meth:`health` to
        ``"hung"``) once the heartbeat goes stale by more than this, and
        interrupts the stuck step where possible (today: an attached
        FaultInjector's interruptible hang; a genuinely wedged device
        call cannot be cancelled — the router fails over around it).
        Set it ABOVE the worst-case legitimate step (first-step compiles
        included) or a cold start reads as a hang. None (default): no
        watchdog thread; :meth:`health` still answers from the
        heartbeat's age when asked.

        ``fault_injector``: a
        :class:`~paddle_tpu.serving.FaultInjector` scripted chaos
        schedule, attached to the engine for the server's lifetime
        (deterministic crash/hang/queue-full tests — never used in
        production serving).

        ``shed_deadlines``: deadline-aware load shedding (OFF by
        default — behavior is bit-identical when False). When on, a
        request whose ``deadline_s`` budget is already below the
        telemetry-estimated queue wait + time-to-first-token is finished
        with ``finish_reason="deadline"`` at submit/admission, BEFORE
        its prefill burns FLOPs a doomed stream can never repay.

        ``metrics_store``: a
        :class:`~paddle_tpu.profiler.metrics_store.MetricsStore` (or
        ``True`` for a default-sized one) — the serve loop feeds every
        gauge and counter into it as monotonic-stamped time series
        (throttled to ``metrics_interval_s``) and the token hot path
        appends per-tenant latency samples, giving windowed
        rate/mean/quantile queries over time. None (the default) costs
        a single detached-attribute check per site — same budget as
        the flight recorder.

        ``slos``: a list of :class:`~paddle_tpu.profiler.slo.SLO`
        objectives — arms the SLO engine (evaluated from the store
        every ``slo_interval_s`` on the loop, and on demand via
        :meth:`slo_report`), maintaining the multi-window burn-rate
        alerts and the ``slo_burn_rate{slo=...}`` /
        ``slo_breached{slo=...}`` gauges. Implies a metrics store.

        ``pathology_detectors``: live pathology detectors subscribed
        to the flight recorder's completed StepRecords (ramp-thrash,
        host-sync regression, spec-acceptance collapse, adapter-swap
        storm, swap-stall — ``explain_tail``'s taxonomy as streaming
        alerts). None (default) arms the standard set when BOTH a
        metrics store and a flight recorder are attached; an explicit
        list overrides; ``False`` disables.

        ``black_box``: a
        :class:`~paddle_tpu.profiler.black_box.BlackBox` (or a
        directory path string, or ``True`` for the default
        ``./debug_bundles``) — arms AUTOMATIC postmortem bundle dumps:
        crash→restart, watchdog hang verdict, and metrics-store alert
        RAISE (edge-triggered per alert instance) each write one
        bounded debug bundle (flight-recorder ring tail, metrics
        series tails, alert log, engine/pool snapshot, worst tail
        gaps). Manual dumps via :meth:`dump_debug_bundle` work with or
        without an armed instance. None (default): no automatic dumps,
        zero hot-path cost."""
        if pipeline_depth is not None and pipeline_depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1, "
                             f"got {pipeline_depth}")
        self.replica = replica
        if flight_recorder is True:
            from ..profiler.flight_recorder import FlightRecorder
            flight_recorder = FlightRecorder(replica=replica)
        if flight_recorder is not None and replica is not None \
                and flight_recorder.replica is None:
            flight_recorder.replica = replica
        self.flight_recorder = flight_recorder
        self.engine = engine
        # the engine knows its own safe depth (see
        # LLMEngine.max_pipeline_depth's contract table): 3 for fused
        # engines (dense, and paged on a full pool — the scheduler
        # mirrors device lens, and the in-flight write fence makes
        # eviction safe), 2 for fused oversubscribed paged and the
        # legacy dense/spec engines, 1 for legacy paged. The DEFAULT
        # stays 2 — the pre-stride contract — so deeper pipelining is
        # an explicit opt-in (pipeline_depth=3); the loop keeps up to
        # depth dispatches in flight before blocking on the oldest sync.
        self.pipeline_depth = min(int(pipeline_depth or 2),
                                  engine.max_pipeline_depth())
        self.poll_interval_s = float(poll_interval_s)
        self.telemetry = telemetry or ServingTelemetry(replica=replica)
        if replica is not None and self.telemetry.replica is None:
            self.telemetry.replica = replica
        self._queue = AdmissionQueue(max_queue_size)
        self._handles: dict[int, RequestHandle] = {}
        # PADDLE_TPU_LOCK_CHECKS=1: acquisition edges feed the PTL004
        # lock-order watchdog (paddle_tpu.analysis.lock_watchdog)
        self._hlock = _lockwatch.tracked(threading.Lock(),
                                         "AsyncLLMServer._hlock")
        self._next_id = 0
        # last engine-stat values the kv_ship telemetry counters have
        # absorbed (see _update_gauges — ship bookings come from two
        # threads, so step-window deltas would miss some)
        self._ship_seen: dict[str, int] = {}
        self._work_evt = threading.Event()
        self._thread = None
        self._accepting = False
        self._stopping = False
        self._crashed = None
        self._saved_callback = None
        self._saved_recorder = None
        # ---- fault tolerance (supervise / watchdog / chaos) ----------
        self.supervise = supervise
        self.step_timeout_s = (float(step_timeout_s)
                               if step_timeout_s is not None else None)
        self.fault_injector = fault_injector
        self.shed_deadlines = bool(shed_deadlines)
        # ---- SLO sensor layer (metrics store / SLOs / detectors) -----
        if metrics_store is True or (slos and not metrics_store):
            from ..profiler.metrics_store import MetricsStore
            metrics_store = MetricsStore()
        # normalize falsy (False, mirroring pathology_detectors=False)
        # to the detached None off-path — `False is not None` would
        # otherwise sail past every off-path check into store calls
        self.metrics_store = metrics_store or None
        self.metrics_interval_s = float(metrics_interval_s)
        self.slo_interval_s = float(slo_interval_s)
        self.slo_engine = None
        if slos:
            from ..profiler.slo import SLOEngine
            self.slo_engine = SLOEngine(slos, self.metrics_store,
                                        telemetry=self.telemetry)
        if pathology_detectors is None and self.metrics_store is not None \
                and self.flight_recorder is not None:
            from ..profiler.slo import default_detectors
            pathology_detectors = default_detectors(self.metrics_store,
                                                    self.telemetry)
        self.pathology_detectors = list(pathology_detectors or ())
        self._ms_last_t = 0.0       # metrics-store feed throttle
        self._slo_last_t = 0.0      # SLO evaluation throttle
        # ---- postmortem black box ------------------------------------
        if black_box:
            from ..profiler.black_box import BlackBox
            if black_box is True:
                black_box = BlackBox()
            elif isinstance(black_box, (str, os.PathLike)):
                black_box = BlackBox(out_dir=black_box)
        self.black_box = black_box or None
        #: mint a TraceContext per submitted request (False exists for
        #: the bench's on/off overhead A/B; caller-supplied contexts
        #: are honored either way)
        self.trace_context = bool(trace_context)
        #: alert instances whose RAISE already triggered a bundle —
        #: (kind, labels, raised_t) identities, so a long-burning alert
        #: dumps once at its raise edge, not once per feed pass
        self._bb_alerts_seen: set = set()
        #: restarts consumed this lifetime (reset by start())
        self.restarts = 0
        self._heartbeat = None      # time.monotonic() of the last loop pass
        self._hung = False          # watchdog verdict (loop pass clears it)
        self._recovering = False    # True between a crash and its re-arm
        self._saved_injector = None
        self._wd_stop = threading.Event()
        self._wd_thread = None

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._saved_callback = self.engine.stream_callback
        self.engine.stream_callback = self._on_token
        if self.flight_recorder is not None:
            self._saved_recorder = self.engine.flight_recorder
            self.engine.flight_recorder = self.flight_recorder
        if self.fault_injector is not None:
            self._saved_injector = self.engine.fault_injector
            self.engine.fault_injector = self.fault_injector
            self.fault_injector._telemetry = self.telemetry
        if self.pathology_detectors and self.flight_recorder is not None:
            for d in self.pathology_detectors:
                # a fresh lifetime evaluates a fresh window: no
                # StepRecords (or active alerts) from a previous run
                d.reset()
                self.flight_recorder.subscribe(d.on_step)
        self._accepting = True
        self._stopping = False
        self._crashed = None  # a restarted server starts clean
        self.restarts = 0
        self._heartbeat = None
        self._hung = False
        self._recovering = False
        self.telemetry.reset()
        self._thread = threading.Thread(target=self._loop,
                                        name="paddle-tpu-serving",
                                        daemon=True)
        self._thread.start()
        if self.step_timeout_s is not None:
            self._wd_stop.clear()
            self._wd_thread = threading.Thread(
                target=self._watchdog_loop, name="paddle-tpu-watchdog",
                daemon=True)
            self._wd_thread.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Stop the engine thread. ``drain=True`` serves every accepted
        request to completion first; ``drain=False`` cancels everything
        outstanding.

        A join that times out raises :exc:`TimeoutError` WITHOUT
        detaching anything — the engine thread still owns the engine
        (it may be inside a long compile, an injected hang, or a
        supervised restart's backoff); a second ``stop()`` keeps
        waiting. A supervised restart already in progress when stop()
        lands is allowed to COMPLETE: with ``drain=True`` the resumed
        requests then serve out token-exactly before the loop exits,
        with ``drain=False`` they are cancelled at the first post-
        recovery sweep."""
        if self._thread is None:
            return
        self._accepting = False
        if not drain:
            with self._hlock:
                handles = list(self._handles.values())
            for h in handles:
                h.cancel_requested = True
        self._stopping = True
        self._wake()
        self._thread.join(timeout)
        if self._thread.is_alive():
            # join timed out: the engine thread still owns the engine —
            # do NOT detach it (a restart would race two threads over one
            # engine); the caller can stop() again with a longer timeout
            raise TimeoutError(
                f"serving loop did not stop within {timeout}s (it may be "
                f"inside a long compile); still draining — call stop() "
                f"again to keep waiting")
        self._thread = None
        if self._wd_thread is not None:
            self._wd_stop.set()
            self._wd_thread.join()
            self._wd_thread = None
        self.engine.stream_callback = self._saved_callback
        if self.flight_recorder is not None:
            if self.pathology_detectors:
                for d in self.pathology_detectors:
                    self.flight_recorder.unsubscribe(d.on_step)
            self.engine.flight_recorder = self._saved_recorder
        if self.fault_injector is not None:
            self.engine.fault_injector = self._saved_injector
        if self._crashed is not None:
            raise RuntimeError(
                f"serving loop crashed: {self._crashed}") from self._crashed

    def health(self):
        """Point-in-time health probe — answerable from ANY thread, even
        (especially) while the serve loop is wedged. States:

        * ``"running"`` — loop thread alive and heartbeating: healthy.
        * ``"hung"`` — thread alive but the heartbeat is stale past
          ``step_timeout_s`` (watchdog verdict, or computed right here
          when no watchdog thread runs): the loop is stuck inside a
          step. The replica router fails over on this state while the
          thread still lives.
        * ``"restarting"`` — a supervised recovery is between crash and
          re-arm (backoff/reset/re-admission). The router places nothing
          here but does NOT evict: the resumption is about to happen.
        * ``"crashed"`` — terminal (no policy, or restarts exhausted).
        * ``"stopped"`` — not started, or stopped.

        Only ``"running"`` is healthy."""
        now = time.monotonic()
        thread = self._thread
        alive = thread is not None and thread.is_alive()
        hb = self._heartbeat
        age = (now - hb) if hb is not None else None
        if self._crashed is not None:
            state = "crashed"
        elif not alive:
            state = "stopped"
        elif self._recovering:
            state = "restarting"
        elif self._hung or (self.step_timeout_s is not None
                            and age is not None
                            and age > self.step_timeout_s):
            state = "hung"
        else:
            state = "running"
        return {"state": state, "healthy": state == "running",
                "heartbeat_age_s": age, "restarts": self.restarts,
                "thread_alive": alive}

    def evict_request(self, request_id, reason="evicted"):
        """Force-finish one request from ANY thread, without the engine
        thread's help — the router's hung-replica failover hook. The
        handle detaches immediately (no further tokens can reach it) and
        finishes with ``finish_reason=reason`` carrying every token
        emitted so far. The engine is NOT touched: if the wedged loop
        later revives, the zombie slot decodes to a finish whose output
        is dropped (its handle is gone) and frees its pool blocks
        normally. Returns the detached handle, or None if unknown/done."""
        with self._hlock:
            h = self._handles.pop(request_id, None)
        if h is None or h.done:
            return None
        self._queue.remove(h)
        self._finish_handle(h, h.full_stream(), reason)
        return h

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc == (None, None, None))
        return False

    def _wake(self):
        self._work_evt.set()

    # -- submission ------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=64, temperature=0.0,
               top_p=1.0, eos_token_id=None, deadline_s=None, block=True,
               timeout=None, routing=None, resume_tokens=None,
               readout_stride=None, adapter_id=0,
               kind="generate", spec_ewma=None, request_id=None,
               export_kv=False, trace_ctx=None) -> RequestHandle:
        """Submit one generation request; returns its streaming
        :class:`RequestHandle`.

        Backpressure: when the admission queue is at capacity, blocks
        (``block=True``, up to ``timeout`` seconds) or raises
        :class:`ServerQueueFull` immediately. Validation errors (empty or
        over-capacity prompt) raise ValueError synchronously.
        ``deadline_s`` is a relative budget: once exceeded, the request is
        cancelled wherever it is (queued or mid-decode) with
        finish_reason ``"deadline"`` and its slot / pool blocks free at
        the next step boundary.

        ``routing``: opaque metadata dict (a routing key, or the
        ReplicaRouter's placement record). Surfaced verbatim on
        ``ServeResult.routing`` and stamped into the request's trace
        timeline as a ``"routed"`` span, so placement decisions are
        per-request observable (``explain_tail`` carries them on tail
        entries).

        ``resume_tokens``: tokens this request already streamed on a
        PREVIOUS server (the router's ``resume_inflight`` failover):
        admission prefills prompt⊕resume_tokens so the stream continues
        token-exactly — only new tokens stream out of the handle, the
        terminal result carries the full sequence, and they count
        against ``max_new_tokens`` (the ORIGINAL total budget).

        ``readout_stride``: latency-tier pin for multi-step decode —
        ``readout_stride=1`` forces every all-decode step this request
        is resident in to sync the host per step (minimum inter-token
        latency for this stream, at the whole batch's throughput cost).
        None (default) inherits the engine's stride.

        ``adapter_id``: the request's TENANT (batched multi-LoRA) — a
        registered id in the engine's adapter store, 0 = base model.
        ``kind="embed"`` marks the request prefill-only (use
        :meth:`submit_embed`).

        ``spec_ewma``: carried draft-acceptance EWMA for a speculative
        engine's acceptance-adaptive verify-k (the router forwards the
        dead replica's learned value on failover — see
        ``LLMEngine.spec_ewma_for``). None lets the engine learn from
        scratch; inert on non-speculative engines.

        ``request_id``: explicit id override (disaggregated serving: a
        request migrated from a prefill replica must keep ITS id on the
        decode replica — the engine's swap-store restore validates by
        rid, and the per-(rid, position) sampling keys make the sampled
        continuation token-exact only under the same rid). Rejects ids
        this server already tracks; ``_next_id`` stays monotonic past it.

        ``export_kv``: stage this request's committed KV as a shippable
        export entry when it finishes (the router's prefill leg) — see
        ``LLMEngine.export_kv``.

        ``trace_ctx``: the request's distributed
        :class:`~paddle_tpu.serving.types.TraceContext` (or its dict
        form) — supplied by the router (which minted it at fleet entry
        and hop-increments it across ship/failover/retry
        resubmissions); MINTED HERE when absent, so every request has
        one. Stamped on the recorder timeline, carried on the
        ``GenerationRequest``, surfaced on ``ServeResult.trace_ctx``."""
        if self._crashed is not None:
            raise ServerClosed(
                f"serving loop crashed: {self._crashed}") from self._crashed
        if not self._accepting:
            raise ServerClosed("server is not accepting requests")
        eng = self.engine
        ids = np.asarray(
            prompt_ids.numpy() if hasattr(prompt_ids, "numpy")
            else prompt_ids, dtype=np.int32).reshape(-1)
        resume = [int(t) for t in resume_tokens] if resume_tokens else None
        total = len(ids) + len(resume or [])
        # fail fast on the submitter's thread, mirroring add_request's
        # checks (the engine would only see the prompt much later) —
        # tenant/kind first, because the capacity bound depends on the
        # kind (an embed prompt needs NO decode headroom)
        if len(ids) == 0:
            raise ValueError("empty prompt")
        adapter_id = int(adapter_id or 0)
        if adapter_id:
            store = getattr(eng, "adapter_store", None)
            if store is None:
                raise ValueError(
                    f"adapter_id {adapter_id} on an engine without an "
                    f"adapter_store")
            if not store.has(adapter_id):
                raise ValueError(f"unknown adapter_id {adapter_id}")
        if kind not in ("generate", "embed"):
            raise ValueError(f"unknown request kind {kind!r}")
        embed_only = getattr(eng, "embed_only", False)
        if kind == "embed":
            if not embed_only and getattr(eng, "scheduler", "") != "fused":
                raise ValueError(
                    "embedding requests need a fused-scheduler engine "
                    "(or an embed-only encoder engine)")
            max_new_tokens = 0
            cap = eng.capacity if embed_only else eng.capacity - 1
            if total > cap:
                raise ValueError(
                    f"embedding prompt of {total} tokens exceeds the "
                    f"engine capacity ({cap})")
        else:
            if embed_only:
                raise ValueError("this server wraps an embed-only "
                                 "encoder engine — use submit_embed()")
            if total >= eng.capacity - eng.speculative_k:
                raise ValueError(
                    f"prompt of {total} tokens leaves no room to "
                    f"generate (engine capacity {eng.capacity})")
        if eng.cache_impl == "paged" and \
                eng.prefill_blocks_needed(total) > eng.n_blocks:
            raise ValueError(
                f"prompt of {total} tokens cannot prefill into the "
                f"{eng.n_blocks}-block pool")
        with self._hlock:
            if request_id is not None:
                rid = int(request_id)
                if rid in self._handles:
                    raise ValueError(
                        f"request_id {rid} is already tracked by this "
                        f"server")
                self._next_id = max(self._next_id, rid + 1)
            else:
                rid = self._next_id
                self._next_id += 1
        now = time.monotonic()
        if readout_stride is not None and int(readout_stride) < 1:
            raise ValueError(f"readout_stride must be >= 1, got "
                             f"{readout_stride}")
        # the trace context propagation rule: accept the caller's (the
        # router hop-increments across resubmissions), mint at this
        # entry point otherwise — every request has exactly one trace_id
        # from its very first hop
        tc = TraceContext.coerce(trace_ctx)
        if tc is None and self.trace_context:
            tc = TraceContext.mint("submit")
        req = ServeRequest(
            rid, ids, int(max_new_tokens), float(temperature), float(top_p),
            eos_token_id,
            deadline=(now + float(deadline_s)
                      if deadline_s is not None else None),
            submitted_at=now,
            routing=dict(routing) if routing is not None else None,
            resume_tokens=resume,
            readout_stride=(int(readout_stride)
                            if readout_stride is not None else None),
            adapter_id=adapter_id, kind=kind,
            spec_ewma=(float(spec_ewma) if spec_ewma is not None
                       else None),
            export_kv=bool(export_kv), trace_ctx=tc)
        handle = RequestHandle(self, req)
        if kind == "embed":
            self.telemetry.inc("embed_requests")
        rec = self.flight_recorder
        if self.shed_deadlines and deadline_s is not None:
            est = self._admission_estimate_s()
            if float(deadline_s) < est:
                # doomed before its prefill would even start: shed NOW,
                # before it burns FLOPs a dead stream can never repay.
                # Counters stay reconcilable with the admission-side
                # shed (which routes through _finish_handle): every
                # submitted request finishes exactly once.
                self.telemetry.inc("requests_submitted")
                self.telemetry.inc("requests_shed_deadline")
                self.telemetry.inc("requests_finished")
                # per-tenant + store accounting like every other finish
                # path: a tenant whose traffic is being shed must show
                # it in ITS e2e series, not vanish from the report
                shed_e2e = time.monotonic() - now
                self.telemetry.observe("e2e_s", shed_e2e,
                                       tenant=adapter_id)
                if self.metrics_store is not None:
                    self.metrics_store.observe("e2e_s", shed_e2e,
                                               tenant=adapter_id)
                if rec is not None:
                    rec.req_event(rid, "queued")
                    rec.set_trace_ctx(rid, tc)
                    rec.req_event(rid, "finish", value="deadline")
                handle._finish(ServeResult(
                    rid, list(resume or []), "deadline", True,
                    e2e_s=0.0, routing=req.routing, trace_ctx=tc))
                return handle
        with self._hlock:
            self._handles[rid] = handle
        if rec is not None:
            # BEFORE the put: once the handle is in the queue the engine
            # thread may admit it (and emit "admitted"/token events)
            # concurrently — "queued" must already be the timeline head
            rec.req_event(rid, "queued")
            rec.set_trace_ctx(rid, tc)
            if req.routing is not None:
                rec.req_event(rid, "routed", value=dict(req.routing))
        try:
            fi = self.fault_injector
            if fi is not None:
                # injected queue_full bursts ride the SAME rejection
                # bookkeeping as a genuinely full queue
                fi.on_submit(self)
            # the RE-ADMISSION grant: a failover resume (tokens already
            # streamed on a previous replica — possibly restored from
            # its host KV tier) jumps the queue; its consumer is already
            # mid-stream, so queueing it behind fresh arrivals converts
            # a swap-sized stall into a whole queue wait
            self._queue.put(handle, block=block, timeout=timeout,
                            front=resume is not None)
        except Exception:
            with self._hlock:
                self._handles.pop(rid, None)
            self.telemetry.inc("requests_rejected_queue_full")
            if rec is not None:   # terminal: the timeline must not leak
                rec.req_event(rid, "finish", value="rejected_queue_full")
            raise
        if self._stopping or self._crashed is not None:
            # TOCTOU with stop(): the loop may have taken its final exit
            # look at the queue before our put landed — undo (unless the
            # loop already picked the handle up, in which case it's safe)
            if self._queue.remove(handle):
                with self._hlock:
                    self._handles.pop(rid, None)
                if rec is not None:
                    rec.req_event(rid, "finish", value="server_stopped")
                raise ServerClosed("server stopped while submitting")
        self.telemetry.inc("requests_submitted")
        self._wake()
        return handle

    def submit_embed(self, prompt_ids, adapter_id=0, deadline_s=None,
                     block=True, timeout=None,
                     routing=None) -> RequestHandle:
        """Submit one PREFILL-ONLY embedding request: no decode tokens,
        no sampling — the prompt's prefill chunks batch into the same
        fused mixed steps as generation traffic, and the terminal
        :class:`ServeResult` carries the mean-pooled final hidden state
        in ``embedding`` (handed back on the prefill sync). Works on a
        fused-scheduler :class:`~paddle_tpu.inference.LLMEngine` (llama
        pooling, optionally per-tenant via ``adapter_id``) and on an
        embed-only encoder engine
        (:class:`~paddle_tpu.serving.embedding.BertEmbedEngine`)."""
        return self.submit(prompt_ids, adapter_id=adapter_id,
                           deadline_s=deadline_s, block=block,
                           timeout=timeout, routing=routing, kind="embed")

    def num_outstanding(self):
        with self._hlock:
            return len(self._handles)

    # -- engine thread ---------------------------------------------------
    def _loop(self):
        """The engine thread's outer SUPERVISOR: run the serve loop; on a
        crash, either recover (``supervise=RestartPolicy``: snapshot
        in-flight requests, reset the engine, re-admit each as
        prompt⊕streamed-tokens and keep serving — token-exact via the
        per-(rid, position) sampling keys) or fail terminally (every
        waiter gets ``finish_reason="server_error"`` carrying its partial
        tokens)."""
        while True:
            try:
                self._serve_loop()
                # clean exit: a stopped replica must not keep scraping
                # as healthy (health() already answers "stopped")
                self.telemetry.set_gauge("server_healthy", 0.0)
                return
            except BaseException as e:
                if not self._recover(e):
                    return

    def _serve_loop(self):
        tel = self.telemetry
        # the in-flight dispatch window, oldest first: up to
        # pipeline_depth step_begin()s run ahead of the oldest sync
        # (depth 2 reproduces the pre-deque loop's exact call sequence:
        # begin, begin, finish | begin, finish | ...)
        pending = collections.deque()
        while True:
            # the watchdog heartbeat: ONE monotonic read per pass (the
            # whole supervision-off/on overhead budget rides on this
            # line staying this cheap)
            self._heartbeat = time.monotonic()
            self._hung = False
            # "other" covers the loop's own bookkeeping (cancel/
            # deadline sweeps, finish routing, gauge sampling) so the
            # attribution explains the busy wall to >= 0.9, not ~0.7
            with tel.stage("other"):
                self._sweep_cancels_and_deadlines()
                self._update_gauges()
            with tel.stage("queue_admit"):
                self._feed_engine()
                self._mark_admission_stalls()
            # THE pipelined-dispatch move: fill the in-flight window
            # before blocking on the oldest step's token transfer
            while len(pending) < self.pipeline_depth:
                try:
                    nxt = self._begin_step()
                except PoolCapacityError as e:
                    # exactly the head-request-can-never-admit signal
                    # (its prompt outgrew the paged pool): fail THAT
                    # request, not the server. Any other error (device,
                    # compile) falls to the supervisor.
                    self._fail_head_waiting(e)
                    break
                if nxt is None:
                    break
                pending.append(nxt)
            if not pending:
                if self._stopping and not self.num_outstanding() \
                        and len(self._queue) == 0:
                    return
                with tel.stage("idle"):
                    self._work_evt.wait(self.poll_interval_s)
                    self._work_evt.clear()
                continue
            done = self._finish_step(pending.popleft())
            if done:
                with tel.stage("other"):
                    self._handle_done(done)

    def _recover(self, exc):
        """Crash handler. Returns True when the serve loop should
        re-enter (supervised restart armed and within budget), False when
        the crash is terminal (every waiter failed attributably)."""
        tel = self.telemetry
        tel.set_gauge("server_healthy", 0.0)
        rec = self.flight_recorder
        pol = self.supervise
        # postmortem black box: capture the crash-time state BEFORE any
        # recovery path resets the engine (the bundle is the last look
        # at what the loop died holding)
        self._black_box_dump("crash", detail=str(exc))
        if pol is None or self.restarts >= pol.max_restarts:
            # terminal: fail every waiter, don't hang them — each result
            # carries the tokens its stream already received (resume
            # prefix from a previous replica included). ORDER matters:
            # _crashed/_accepting flip BEFORE the atomic snapshot+clear,
            # so a racing submit() either sees the flags and raises
            # ServerClosed or lands in the snapshot and gets failed —
            # never a handle nobody will ever finish.
            self._crashed = exc
            self._accepting = False  # submit() must not feed a dead loop
            with self._hlock:
                handles = list(self._handles.values())
                self._handles.clear()
            self._queue.drain()
            for h in handles:
                if h.done:
                    continue
                if rec is not None:
                    rec.req_event(h.request_id, "crashed", value=str(exc))
                h._finish(ServeResult(
                    h.request_id, h.full_stream(),
                    f"server_error: {exc}", True,
                    routing=h.request.routing,
                    trace_ctx=h.request.trace_ctx))
            return False
        # ---- supervised restart --------------------------------------
        with self._hlock:
            handles = [h for h in self._handles.values() if not h.done]
        self._recovering = True
        self.restarts += 1
        tel.inc("engine_restarts")
        resident = [h for h in handles
                    if h.state in (RequestState.PENDING,
                                   RequestState.RUNNING)]
        if rec is not None:
            for h in resident:
                rec.req_event(h.request_id, "crashed", value=str(exc))
        # a crash LOOP must not spin the engine thread
        time.sleep(self.supervise.delay(self.restarts))
        try:
            self.engine.reset()
        except BaseException as reset_exc:  # engine unrecoverable
            self._recovering = False
            self.supervise = None   # force the terminal path
            return self._recover(reset_exc)
        # re-admit every engine-resident request as prompt⊕streamed so
        # its stream CONTINUES (oldest first — the original admission
        # order, so slot/pool layout replays deterministically)
        for h in sorted(resident, key=lambda h: h.request.request_id):
            committed = h.full_stream()
            if self._readmit(h, committed):
                tel.inc("requests_resumed")
                if rec is not None:
                    rec.req_event(h.request_id, "resumed",
                                  value=len(committed))
        self._recovering = False
        self._wake()
        return True

    def _readmit(self, handle, committed):
        """Hand one request to the engine as prompt⊕``committed``
        (tokens it already streamed in a previous life — a supervised
        restart's snapshot, or a failover resume prefix; empty for a
        fresh admission). THE one copy of the re-admission edge cases:
        a stream that already emitted its eos token finishes ``"eos"``
        right here (re-prefilling it would decode PAST the eos — the
        crash merely beat the finished output's routing), an exhausted
        budget finishes ``"length"``, and an engine validation error
        finishes ``"rejected"`` on the `requests_rejected_validation`
        counter. Returns True when the request entered the engine."""
        req = handle.request
        eng = self.engine
        eos = req.eos_token_id
        if committed and eos is not None and committed[-1] == eos:
            self._finish_handle(handle, committed, "eos")
            return False
        remaining = req.max_new_tokens - len(committed)
        if committed and remaining <= 0:
            self._finish_handle(handle, committed, "length")
            return False
        if committed and len(req.prompt_ids) + len(committed) >= \
                eng.capacity - eng.speculative_k:
            # the stream GREW to the engine's buffer edge before the
            # crash/failover: the uninterrupted run would have retired
            # it "capacity" — re-prefilling would only trip add_request
            # validation and mislabel a complete stream as rejected
            self._finish_handle(handle, committed, "capacity")
            return False
        try:
            self.engine.add_request(
                req.prompt_ids, max_new_tokens=remaining,
                temperature=req.temperature, top_p=req.top_p,
                eos_token_id=eos, request_id=req.request_id,
                committed_tokens=committed or None,
                readout_stride=req.readout_stride,
                adapter_id=req.adapter_id, kind=req.kind,
                spec_ewma=req.spec_ewma,
                export_kv=getattr(req, "export_kv", False),
                trace_ctx=req.trace_ctx)
        except ValueError as e:
            # the rejection must be visible in telemetry, not just on
            # the handle — a silent validation drop looks like a lost
            # request to a dashboard
            self.telemetry.inc("requests_rejected_validation")
            self._finish_handle(handle, committed, f"rejected: {e}")
            return False
        handle.state = RequestState.PENDING
        return True

    def _watchdog_loop(self):
        """Stale-heartbeat monitor (armed by ``step_timeout_s``). Flips
        the ``server_healthy`` gauge and the :meth:`health` verdict to
        hung, and interrupts the stuck step where the runtime allows it —
        today that means an attached FaultInjector's interruptible hang
        (the scripted stand-in for a cancellable device call); a
        genuinely wedged dispatch cannot be cancelled from outside, the
        router fails over around it instead."""
        period = min(self.step_timeout_s / 4.0, 0.05)
        while not self._wd_stop.wait(period):
            hb = self._heartbeat
            thread = self._thread
            if (hb is None or self._recovering or self._crashed is not None
                    or thread is None or not thread.is_alive()):
                continue
            if time.monotonic() - hb > self.step_timeout_s \
                    and not self._hung:
                self._hung = True
                self.telemetry.set_gauge("server_healthy", 0.0)
                # the hang VERDICT edge (the loop pass clears _hung, so
                # a re-wedged loop re-triggers) — dump the black box
                # from THIS thread: the wedged loop can't
                self._black_box_dump(
                    "hang",
                    detail=f"heartbeat stale > {self.step_timeout_s}s")
                fi = self.fault_injector
                if fi is not None and fi.hanging:
                    fi.interrupt()

    def _fail_head_waiting(self, err):
        eng = self.engine
        if not eng.waiting:
            raise err  # not a head-of-queue admission failure: re-raise
        req = eng.waiting.popleft()
        # a preemption-grown request may have committed (and streamed)
        # tokens before being parked: _finish_tokens stitches them in AND
        # pops the engine's _preempted_prefix entry (leak otherwise)
        tokens = eng._finish_tokens(req, [])
        with self._hlock:
            h = self._handles.get(req.request_id)
        if h is not None:
            self._finish_handle(h, tokens, f"rejected: {err}")

    def _begin_step(self):
        """engine.step_begin() with its wall split into the prefill
        (admission) dispatch, the decode dispatch, and the host scheduling
        remainder — read back from the engine's own stage stats so the
        attribution can't drift from what the engine measured."""
        eng, tel = self.engine, self.telemetry
        s_admit = eng.stats["admit_time_s"]
        s_disp = eng.stats["dispatch_time_s"]
        s_pre = eng.stats["preemptions"]
        s_ptok = eng.stats["prefill_tokens"]
        s_multi = eng.stats["multi_steps"]
        s_pfx = {k: eng.stats[k] for k in ("prefix_hit_tokens",
                                           "prefix_cow_blocks",
                                           "prefix_evicted_blocks",
                                           "adapter_cache_hits",
                                           "adapter_cache_misses",
                                           "adapter_swaps",
                                           "kv_swap_out_blocks",
                                           "kv_swap_in_blocks",
                                           "kv_swap_saved_tokens",
                                           "kv_spill_blocks",
                                           "kv_promote_blocks")}
        t0 = time.perf_counter()
        pending = eng.step_begin()
        wall = time.perf_counter() - t0
        d_admit = eng.stats["admit_time_s"] - s_admit
        d_disp = eng.stats["dispatch_time_s"] - s_disp
        d_ptok = eng.stats["prefill_tokens"] - s_ptok
        tel.add_stage("prefill_dispatch", d_admit)
        tel.add_stage("decode_dispatch", d_disp)
        tel.add_stage("schedule", max(wall - d_admit - d_disp, 0.0))
        if d_ptok:
            tel.inc("prefill_tokens", d_ptok)
        for key, before in s_pfx.items():
            # prefix-cache activity (hits at admission, COW clones, LRU
            # evictions) AND adapter-cache activity (hit/miss/swap at
            # admission) all happen inside step_begin — the deltas land
            # on the matching telemetry counters
            if eng.stats[key] > before:
                tel.inc(key, eng.stats[key] - before)
        if eng.stats["preemptions"] > s_pre:
            # pool-pressure preemptions happen inside step_begin's
            # allocator loop — this is where the delta is visible
            tel.inc("preemptions", eng.stats["preemptions"] - s_pre)
        if eng.stats["multi_steps"] > s_multi:
            tel.inc("multi_steps", eng.stats["multi_steps"] - s_multi)
        if d_admit > 0.0:
            self._note_admissions()
        return pending

    def _finish_step(self, pending):
        """engine.step_finish() with its wall split into the device→host
        token sync and the readout/emit remainder."""
        eng, tel = self.engine, self.telemetry
        s_sync = eng.stats["host_sync_time_s"]
        s_emit = eng.stats["emit_time_s"]
        # speculative acceptance accounting lands at READOUT (this is
        # where the engine learns which drafts committed)
        s_spec = {k: eng.stats[k] for k in ("spec_proposed_tokens",
                                            "spec_accepted_tokens")}
        t0 = time.perf_counter()
        done = eng.step_finish(pending)
        wall = time.perf_counter() - t0
        d_sync = eng.stats["host_sync_time_s"] - s_sync
        d_emit = eng.stats["emit_time_s"] - s_emit
        tel.add_stage("host_sync", d_sync)
        tel.add_stage("emit", d_emit)
        tel.add_stage("other", max(wall - d_sync - d_emit, 0.0))
        tel.inc("engine_steps")
        for key, before in s_spec.items():
            if eng.stats[key] > before:
                tel.inc(key, eng.stats[key] - before)
        return done

    def _admission_estimate_s(self):
        """Telemetry-estimated latency a fresh submission pays before its
        first token: observed mean queue wait + mean TTFT. 0.0 on a cold
        server (no observations yet) — deadline shedding never fires
        before the estimator has data, so a cold start sheds nothing."""
        tel = self.telemetry
        return tel.queue_wait_s.mean + tel.ttft_s.mean

    def _feed_engine(self):
        """Move queued requests into the engine's waiting deque — only as
        many as could plausibly admit (engine backlog stays ≤ max_batch)
        so queue-wait is measured HERE and cancellation of queued
        requests never has to dig through engine state."""
        eng, tel = self.engine, self.telemetry
        while len(eng.waiting) < eng.B:
            handle = self._queue.pop()
            if handle is None:
                return
            if handle.done:          # cancelled/expired while queued
                continue
            req = handle.request
            resume = list(req.resume_tokens or [])
            if self.shed_deadlines and req.deadline is not None:
                # admission-side shed: the queue wait is already paid,
                # so the bar is the remaining budget vs estimated TTFT
                if req.deadline - time.monotonic() < tel.ttft_s.mean:
                    tel.inc("requests_shed_deadline")
                    self._finish_handle(handle, resume, "deadline")
                    continue
            self._readmit(handle, resume)

    def _update_gauges(self):
        """Sample the point-in-time engine state into the telemetry
        gauges — the Prometheus view of what the flight recorder stamps
        per step. One pass is a handful of O(B) reads; it runs every
        loop iteration so the gauges stay fresh even while idle."""
        eng, tel = self.engine, self.telemetry
        # the loop is provably passing right now — that IS healthy (a
        # watchdog hang verdict or a crash flips it to 0 from outside)
        tel.set_gauge("server_healthy", 1.0)
        tel.set_gauge("queue_depth", len(self._queue))
        tel.set_gauge("engine_waiting", len(eng.waiting))
        tel.set_gauge("running_slots",
                      sum(1 for s in eng.slots if s is not None))
        tel.set_gauge("pipeline_inflight", eng._inflight)
        if eng.cache_impl == "paged":
            free = len(eng._free_blocks)
            tel.set_gauge("kv_pool_free_blocks", free)
            tel.set_gauge("kv_pool_occupancy",
                          1.0 - free / max(eng.n_blocks, 1))
            tel.set_gauge("kv_pool_effective_blocks",
                          eng.kv_pool_effective_blocks())
            # host KV tier traffic (0 with the tier off — the gauges
            # sample the cumulative engine stats, so one scrape shows
            # whether preemptions are converting into copies)
            tel.set_gauge("kv_swap_in_bytes",
                          eng.stats.get("kv_swap_in_bytes", 0))
            tel.set_gauge("kv_swap_out_bytes",
                          eng.stats.get("kv_swap_out_bytes", 0))
            tel.set_gauge("kv_host_spill_blocks",
                          len(getattr(eng, "_spill", ())))
            # the spill store's bound is set in BYTES (kv_host_spill_bytes
            # engine arg) — report occupancy in the bound's own unit too
            tel.set_gauge("kv_host_spill_bytes",
                          getattr(eng, "_spill_bytes", 0))
            # cross-replica ship counters book from BOTH the engine
            # thread (finish-site export, restore import) and the router
            # thread (pull-on-miss peer export) — delta-sync them here,
            # outside any step window, so no booking site is missed
            for key in ("kv_ship_out_blocks", "kv_ship_in_blocks",
                        "kv_ship_out_bytes", "kv_ship_in_bytes"):
                cur = eng.stats.get(key, 0)
                d = cur - self._ship_seen.get(key, 0)
                if d > 0:
                    tel.inc(key, d)
                    self._ship_seen[key] = cur
            if eng.prefix_cache:
                tel.set_gauge("prefix_cached_blocks", len(eng._lru))
                hit = eng.stats["prefix_hit_tokens"]
                pre = eng.stats["prefill_tokens"]
                tel.set_gauge("prefix_cache_hit_rate",
                              hit / (hit + pre) if hit + pre else 0.0)
        cache = getattr(eng, "adapter_cache", None)
        if cache is not None:
            tel.set_gauge("adapter_cache_occupancy", cache.occupancy())
        prop = eng.stats.get("spec_proposed_tokens", 0)
        if prop:
            tel.set_gauge("spec_acceptance_rate",
                          eng.stats["spec_accepted_tokens"] / prop)
        rec = self.flight_recorder
        if rec is not None and rec.enabled:
            last = rec.last_record()
            if last is not None:
                tel.set_gauge("token_budget_utilization",
                              last.budget_utilization)
        # the serve loop provably sampled the gauges this pass: stamp
        # it — gauge_last_sample_age_s ages from HERE (the watchdog's
        # out-of-loop writes deliberately do not refresh it)
        tel.mark_gauge_sample()
        # SLO sensor layer: the off path is this one attribute check
        if self.metrics_store is not None:
            self._feed_sensors()

    def _feed_sensors(self):
        """Feed EVERY gauge and cumulative counter into the metrics
        store as time series (counters stay cumulative — windowed
        ``store.rate()`` turns the deltas into tokens/s,
        preemptions/s, ...) and run the throttled SLO evaluation.
        Called once per loop pass (only with a store attached); both
        halves are interval-gated so a hot loop costs two monotonic
        reads per pass, not a store write per gauge."""
        now = time.monotonic()
        store = self.metrics_store
        if now - self._ms_last_t >= self.metrics_interval_s:
            self._ms_last_t = now
            for name, v in self.telemetry.get_gauges().items():
                if name != "gauge_last_sample_age_s":
                    # the staleness gauge is computed at READ time —
                    # storing the feed-time value would record the
                    # sensor's own cadence, not the loop's health
                    store.observe(name, v, t=now)
            for name, v in self.telemetry.get_counters().items():
                store.observe(name, v, t=now)
        if self.slo_engine is not None \
                and now - self._slo_last_t >= self.slo_interval_s:
            self._slo_last_t = now
            self.slo_engine.evaluate(now=now)
        if self.black_box is not None:
            # alert RAISE edges (burn-rate alerts from the SLO engine,
            # pathology detectors' raises): each alert INSTANCE —
            # identified by (kind, labels, raised_t) — dumps exactly one
            # bundle, at the first feed pass that sees it active
            for a in store.alerts(active_only=True):
                key = (a.kind,
                       tuple(sorted((str(k), str(v))
                                    for k, v in a.labels.items())),
                       round(a.raised_t, 6))
                if key not in self._bb_alerts_seen:
                    self._bb_alerts_seen.add(key)
                    self._black_box_dump(
                        "burn_alert", detail=f"{a.kind}: {a.message}")

    def _black_box_dump(self, reason, detail=None):
        """Best-effort AUTOMATIC bundle dump (crash / hang / alert
        edges). Never raises into the serving loop or the watchdog —
        postmortem capture must not be able to make the incident
        worse. No-op without an armed ``black_box``."""
        bb = self.black_box
        if bb is None:
            return None
        try:
            return bb.dump(reason, server=self, detail=detail)
        except Exception:
            return None

    def dump_debug_bundle(self, path, reason="manual", detail=None):
        """Write one bounded postmortem debug bundle for THIS server to
        ``path`` (JSON: flight-recorder ring tail + worst tail gaps,
        metrics-store series tails + alert log, engine config/pool/
        kv-tier snapshot, health/restart state, injected-fault record).
        Works from ANY thread, with or without an armed ``black_box``
        (manual dumps don't dedup or rotate). Read it back with
        ``python -m paddle_tpu.profiler.bundle <path>``."""
        from ..profiler.black_box import collect_bundle, write_bundle
        return write_bundle(
            collect_bundle(server=self, reason=reason, detail=detail),
            path)

    def slo_report(self):
        """Point-in-time SLO/sensor report — answerable from ANY
        thread: per-SLO burn-rate evaluations (fresh, not the loop's
        last throttled pass), the store's alert log, each pathology
        detector's active flag, and the per-tenant latency snapshot.
        ``text`` carries the human rendering. Works (degenerately) with
        no store attached — empty slos/alerts, but tenant latency
        still reports."""
        from ..profiler.slo import format_slo_report
        store = self.metrics_store
        out = {
            "replica": self.replica,
            "slos": (self.slo_engine.evaluate()
                     if self.slo_engine is not None else []),
            "alerts": ([a.to_dict() for a in store.alerts()]
                       if store is not None else []),
            "pathologies": {d.kind: d.active
                            for d in self.pathology_detectors},
            "tenant_latency": self.telemetry.tenant_latency_snapshot(),
            "gauge_last_sample_age_s":
                self.telemetry.get_gauges()["gauge_last_sample_age_s"],
        }
        out["text"] = format_slo_report(out)
        return out

    def _note_admissions(self):
        """Mark handles whose request just entered an engine slot as
        RUNNING and record their queue wait (submit → slot admission)
        plus the admission stall (first-free-slot → slot admission)."""
        now = time.monotonic()
        with self._hlock:
            handles = dict(self._handles)
        for slot in self.engine.slots:
            if slot is None:
                continue
            h = handles.get(slot.req.request_id)
            if h is not None and h.state is RequestState.PENDING:
                h.state = RequestState.RUNNING
                h.admitted_at = now
                wait = now - h.request.submitted_at
                if self.flight_recorder is not None:
                    self.flight_recorder.req_event(
                        slot.req.request_id, "admitted")
                self.telemetry.inc("requests_admitted")
                self.telemetry.observe("queue_wait_s", wait,
                                       tenant=h.request.adapter_id)
                if self.metrics_store is not None:
                    self.metrics_store.observe(
                        "queue_wait_s", wait, t=now,
                        tenant=h.request.adapter_id)
                self.telemetry.observe(
                    "admission_stall_s",
                    max(now - h.stall_mark, 0.0)
                    if h.stall_mark is not None else 0.0)

    def _mark_admission_stalls(self):
        """Stamp the moment a FREE slot exists for a request that could
        take it; _note_admissions turns the stamp into the
        admission_stall_s observation. Only as many of the OLDEST pending
        requests as there are free slots carry a stamp — the rest are
        waiting on CAPACITY, not on admission, and their marks clear (a
        stamped-then-refilled slot must not convert a capacity wait into
        a reported stall). Under the legacy scheduler the stall covers
        whole admission prefill trains and step horizons; the fused
        scheduler admits on the next loop pass (~0)."""
        eng = self.engine
        free = sum(1 for s in eng.slots if s is None)
        now = time.monotonic()
        with self._hlock:
            handles = list(self._handles.values())
        pending = sorted((h for h in handles
                          if h.state is RequestState.PENDING),
                         key=lambda h: h.request.submitted_at)
        # legacy paged admission also needs POOL blocks for the whole
        # prompt — a free slot over a dry pool is still a capacity wait,
        # not an admission stall (fused admission allocates lazily, so a
        # free slot alone is admissible there). The fused scheduler's
        # admission-defer progress guarantee is mirrored the same way:
        # while a resident slot is still RAMPING, a prompt the pool
        # cannot cover waits on capacity, not on admission.
        paged = eng.cache_impl == "paged"
        legacy_paged = paged and eng.scheduler != "fused"
        fused_ramping = paged and not legacy_paged and any(
            s is not None and s.ramping for s in eng.slots)
        # the fused defer also counts the resident ramps' OUTSTANDING
        # block demand (the engine's exact predicate) — mirroring only
        # the new prompt's need would stamp deferred requests as
        # admission stalls, the precise misclassification this mark
        # discipline exists to avoid
        ramp_deficit = sum(
            max(eng.prefill_blocks_needed(s.prompt_len)
                - len(eng._slot_blocks[i]), 0)
            for i, s in enumerate(eng.slots)
            if s is not None and s.ramping) if fused_ramping else 0
        for i, h in enumerate(pending):
            admissible = i < free and (
                not (legacy_paged or fused_ramping)
                or eng.prefill_blocks_needed(len(h.request.prompt_ids))
                + ramp_deficit <= eng._n_allocatable())
            if admissible:
                if h.stall_mark is None:
                    h.stall_mark = now
            else:
                h.stall_mark = None

    def _sweep_cancels_and_deadlines(self):
        """Apply caller cancellations and expire deadlines. A running
        request's slot (and paged pool blocks) frees RIGHT HERE —
        before the next dispatch — so capacity returns to the pool
        immediately, not after the stream drains."""
        eng = self.engine
        now = time.monotonic()
        with self._hlock:
            items = list(self._handles.items())
        for rid, h in items:
            if h.done:
                continue
            expired = h.request.deadline is not None \
                and now > h.request.deadline
            if not h.cancel_requested and not expired:
                continue
            reason = "cancelled" if h.cancel_requested else "deadline"
            # a still-queued handle has generated nothing HERE, but a
            # failover resume carries its previous replica's tokens
            tokens = list(h.request.resume_tokens or [])
            if h.state is RequestState.QUEUED:
                self._queue.remove(h)
            else:
                out = eng.cancel(rid, reason=reason)
                if out is not None:
                    eng.finished_outputs.pop(rid, None)
                    tokens = out.token_ids
            self.telemetry.inc("requests_expired" if reason == "deadline"
                               else "requests_cancelled")
            self._finish_handle(h, tokens, reason)

    def _on_token(self, rid, tok):
        """Engine stream callback (fires inside step_finish's readout):
        route the token to its handle and record TTFT / inter-token.
        The stamp is BACKDATED by the engine's ``emit_backdate_s`` — a
        k-step batched readout drains k tokens in one sync, but each
        was produced at its own device step boundary, so histograms see
        k amortized gaps instead of k-1 zeros and one stride-wide
        spike. Clamped monotonic per handle (pipelined strides can
        backdate into the previous readout's window)."""
        with self._hlock:
            h = self._handles.get(rid)
        if h is None:
            return
        now = time.monotonic() - self.engine.emit_backdate_s
        if h.last_token_at is not None and now < h.last_token_at:
            now = h.last_token_at
        tenant = h.request.adapter_id
        store = self.metrics_store
        if h.first_token_at is None:
            ttft = max(now - h.request.submitted_at, 0.0)
            self.telemetry.observe("ttft_s", ttft, tenant=tenant)
            if store is not None:
                store.observe("ttft_s", ttft, t=now, tenant=tenant)
        elif h.last_token_at is not None:
            gap = now - h.last_token_at
            self.telemetry.observe("inter_token_s", gap, tenant=tenant)
            if store is not None:
                store.observe("inter_token_s", gap, t=now, tenant=tenant)
        self.telemetry.inc("tokens_emitted")
        self.telemetry.inc_tenant(tenant)
        h._emit(tok, t=now)

    def _handle_done(self, outputs):
        for out in outputs:
            self.engine.finished_outputs.pop(out.request_id, None)
            with self._hlock:
                h = self._handles.get(out.request_id)
            if h is None:
                continue
            emb = getattr(out, "embedding", None)
            if emb is not None:
                # per-tenant accounting: an embed request's processed
                # tokens are its pooled prompt positions
                self.telemetry.inc_tenant(h.request.adapter_id,
                                          len(h.request.prompt_ids))
            self._finish_handle(h, out.token_ids, out.finish_reason,
                                embedding=emb)

    def _finish_handle(self, handle, token_ids, reason, embedding=None):
        now = time.monotonic()
        req = handle.request
        trace = None
        rec = self.flight_recorder
        if rec is not None and rec.enabled:
            rec.req_event(handle.request_id, "finish", value=reason)
            trace = rec.request_trace(handle.request_id)
        result = ServeResult(
            handle.request_id, list(token_ids), reason, True,
            ttft_s=(handle.first_token_at - req.submitted_at
                    if handle.first_token_at is not None else None),
            e2e_s=now - req.submitted_at,
            queue_wait_s=(handle.admitted_at - req.submitted_at
                          if handle.admitted_at is not None else None),
            trace=trace, routing=req.routing, embedding=embedding,
            trace_ctx=req.trace_ctx)
        self.telemetry.inc("requests_finished")
        self.telemetry.observe("e2e_s", result.e2e_s,
                               tenant=req.adapter_id)
        if self.metrics_store is not None:
            self.metrics_store.observe("e2e_s", result.e2e_s, t=now,
                                       tenant=req.adapter_id)
        with self._hlock:
            self._handles.pop(handle.request_id, None)
        handle._finish(result)
