"""Embed-only encoder engine — non-generative serving through the same
:class:`~paddle_tpu.serving.AsyncLLMServer` front-end.

Reference analog: the reference's AnalysisPredictor front-end serves
classification/embedding models through the same predictor surface as
generative ones (PAPER.md §1, layer 6c). Here the llama engine already
serves prefill-only embedding requests INSIDE its fused token-budget walk
(``LLMEngine.add_request(kind="embed")``); this module is the second
half of the scenario-diversity story: a bidirectional ENCODER (bert) has
no KV cache and no decode loop at all, so it gets its own minimal engine
speaking the ``step_begin``/``step_finish`` protocol — one compiled
full-sequence forward per batch, masked mean-pool of the final hidden
states, everything else (admission queue, backpressure, deadlines,
telemetry, supervision) inherited from the server unchanged.

Static shapes: one ``[max_batch, max_seq_len]`` program serves every
batch composition (shorter prompts pad, the attention mask hides the
padding, and the pooled mean divides by the true lengths).
"""
from __future__ import annotations

import collections
import dataclasses
import time

import numpy as np

from ..inference.llm_engine import (RequestOutput,
                                    close_thread_stride_guard,
                                    default_engine_stats)

__all__ = ["BertEmbedEngine"]


@dataclasses.dataclass
class _EmbedRequest:
    request_id: int
    prompt_ids: np.ndarray
    adapter_id: int = 0
    kind: str = "embed"
    max_new_tokens: int = 0
    temperature: float = 0.0
    top_p: float = 1.0
    eos_token_id: int | None = None
    readout_stride: int | None = None


class _BSlot:
    __slots__ = ("req",)

    def __init__(self, req):
        self.req = req


class _EmbedPending:
    __slots__ = ("out", "batch", "t_dispatch")

    def __init__(self, out, batch, t_dispatch):
        self.out = out          # device [B, H] pooled rows
        self.batch = batch      # [(row, _BSlot), ...]
        self.t_dispatch = t_dispatch


class BertEmbedEngine:
    """Prefill-only serving engine over a bert encoder
    (:class:`~paddle_tpu.models.bert.BertModel` or
    ``BertForMaskedLM``). Speaks the slice of the LLMEngine protocol
    :class:`~paddle_tpu.serving.AsyncLLMServer` drives — submit through
    ``server.submit_embed(...)``; every result carries the masked
    mean-pooled final hidden state."""

    #: the server routes every submission through submit_embed and
    #: rejects generation kinds up front
    embed_only = True

    def __init__(self, model, max_batch=8, max_seq_len=None):
        bert = getattr(model, "bert", model)
        self.model = model
        self._bert = bert
        c = bert.config
        model.eval()
        self.B = int(max_batch)
        self.capacity = int(max_seq_len or c.max_position_embeddings)
        if self.capacity > c.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.capacity} exceeds the position table "
                f"({c.max_position_embeddings})")
        # the LLMEngine surface the server reads
        self.speculative_k = 1
        self.cache_impl = "dense"
        self.scheduler = "fused"
        self.prefix_cache = False
        self.readout_stride = 1
        self.horizon = 1
        self.stream_callback = None
        self.flight_recorder = None
        self.fault_injector = None
        self.waiting = collections.deque()
        self.slots = [None] * self.B
        self.finished_outputs = {}
        self._next_id = 0
        self._inflight = 0
        self._cancelled = set()
        self._fn = None
        self._state = None
        self._state_vals = None
        # the serving layer reads stats keys by name — share LLMEngine's
        # schema so a future counter can never silently drift
        self.stats = default_engine_stats()

    # -- protocol surface ----------------------------------------------
    def max_pipeline_depth(self):
        return 1     # one batch in flight; the sync IS the result

    def tp_degree(self):
        return 1

    def prefill_blocks_needed(self, prompt_len):
        return 0     # no paged pool

    def probe_prefix_len(self, token_ids, chain_hashes=None, adapter_id=0):
        return 0

    def prefix_chain_hashes(self, token_ids, adapter_id=0):
        return []

    def reset(self):
        """Supervised-restart hook: drop every resident/waiting request
        binding (the server re-admits from its own snapshot)."""
        self.waiting.clear()
        self.slots = [None] * self.B
        self.finished_outputs.clear()
        self._cancelled.clear()
        self._inflight = 0
        return self

    def add_request(self, prompt_ids, request_id=None, adapter_id=0,
                    kind="embed", **_ignored):
        ids = np.asarray(
            prompt_ids.numpy() if hasattr(prompt_ids, "numpy")
            else prompt_ids, dtype=np.int32).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if len(ids) > self.capacity:
            raise ValueError(f"prompt of {len(ids)} tokens exceeds the "
                             f"encoder capacity {self.capacity}")
        if kind != "embed":
            raise ValueError("BertEmbedEngine serves embedding requests "
                             "only (kind='embed')")
        if adapter_id:
            raise ValueError("BertEmbedEngine has no adapter store")
        rid = self._next_id if request_id is None else request_id
        self._next_id = max(self._next_id, rid) + 1
        self.waiting.append(_EmbedRequest(rid, ids))
        self.stats["embed_requests"] += 1
        return rid

    def has_unfinished(self):
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def cancel(self, request_id, reason="cancelled"):
        for i, req in enumerate(self.waiting):
            if req.request_id == request_id:
                del self.waiting[i]
                out = RequestOutput(request_id, [], True, reason)
                self.finished_outputs[request_id] = out
                return out
        for b, slot in enumerate(self.slots):
            if slot is not None and slot.req.request_id == request_id:
                # the batch is already on device; drop the row at readout
                self._cancelled.add(request_id)
                self.slots[b] = None
                out = RequestOutput(request_id, [], True, reason)
                self.finished_outputs[request_id] = out
                return out
        return None

    # -- compiled program ----------------------------------------------
    def _programs(self):
        if self._fn is not None:
            return
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor, functional_mode
        from ..jit.functional_call import (bind_state, collect_state,
                                           read_values)

        _, params, _, buffers = collect_state(self.model)
        state = params + buffers
        self._state = state
        self._state_vals = read_values(state)
        bert = self._bert

        def embed(state_vals, ids, mask):
            with functional_mode(), bind_state(state, state_vals):
                seq, _ = bert(Tensor(ids), None, Tensor(mask))
                seqv = seq._value.astype(jnp.float32)
            m = mask.astype(jnp.float32)[:, :, None]
            return (seqv * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)

        self._fn = jax.jit(embed)

    # -- the step protocol ---------------------------------------------
    def step_begin(self):
        # step-protocol contract: close the calling thread's open
        # transfer-guard stride window (another engine interleaved on
        # this thread may have armed it — this dispatch legitimately
        # re-opens host->device traffic)
        close_thread_stride_guard()
        if self._inflight:
            return None          # depth 1: the sync IS the result
        if not self.waiting:
            return None
        t0 = time.perf_counter()
        self._programs()
        batch = []
        ids = np.zeros((self.B, self.capacity), np.int32)
        mask = np.zeros((self.B, self.capacity), np.int32)
        row = 0
        while self.waiting and row < self.B:
            req = self.waiting.popleft()
            P = len(req.prompt_ids)
            ids[row, :P] = req.prompt_ids
            mask[row, :P] = 1
            slot = _BSlot(req)
            self.slots[row] = slot
            batch.append((row, slot))
            self.stats["prefill_tokens"] += P
            self.stats["prefill_chunks"] += 1
            row += 1
        self.stats["admit_time_s"] += time.perf_counter() - t0
        t0 = time.perf_counter()
        out = self._fn(self._state_vals, ids, mask)
        dt = time.perf_counter() - t0
        self.stats["dispatch_time_s"] += dt
        self.stats["decode_time_s"] += dt
        self.stats["fused_steps"] += 1
        self._inflight += 1
        return _EmbedPending(out, batch, t0)

    def step_finish(self, pending):
        # as in LLMEngine.step_finish: the readout below must not run
        # inside another engine's disallow window on this thread
        close_thread_stride_guard()
        t0 = time.perf_counter()
        rows = np.asarray(pending.out, np.float32)   # THE sync
        dt = time.perf_counter() - t0
        self.stats["host_sync_time_s"] += dt
        self.stats["decode_time_s"] += dt
        self.stats["steps"] += 1
        self._inflight -= 1
        done = []
        t0 = time.perf_counter()
        for row, slot in pending.batch:
            rid = slot.req.request_id
            if self.slots[row] is not slot or rid in self._cancelled:
                self._cancelled.discard(rid)
                continue         # cancelled mid-flight: row dropped
            out = RequestOutput(rid, [], True, "embed",
                                embedding=rows[row])
            self.finished_outputs[rid] = out
            done.append(out)
            self.slots[row] = None
        self.stats["emit_time_s"] += time.perf_counter() - t0
        return done

    def throughput(self):
        dt = self.stats["decode_time_s"]
        return self.stats["prefill_tokens"] / dt if dt > 0 else 0.0

    def reset_stats(self):
        for key in self.stats:
            self.stats[key] = 0.0 if key.endswith("_s") else 0
