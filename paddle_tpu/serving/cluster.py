"""Multichip serving — the two-level cluster subsystem.

Reference analog: the reference's fleet/auto_parallel orchestration layer
(PAPER.md §1, layer 6a) over its AnalysisPredictor serving front-end
(layer 6c): capacity scales with CHIPS, not with engine slots. Two
independent levels compose:

* **Level 1 — tensor parallelism** (:func:`tp_engine`): one
  :class:`~paddle_tpu.inference.LLMEngine` whose weights AND paged KV
  pools shard across a ``("tp",)`` mesh axis. kv-heads are the natural
  shard dim — the Pallas paged-attention grid is ``(batch, kv_head,
  max_blocks)``, so each shard keeps its own physical pool slice and the
  per-shard kernel is byte-identical to the single-chip one at
  ``Hkv/ntp`` heads (``paged_attention_decode_tp`` /
  ``paged_attention_append_tp`` shard_map it; the CPU dense fallback
  partitions under GSPMD). Block tables, the allocator, and the prefix
  cache's content hashing stay HOST-GLOBAL and TP-oblivious; the
  vocab-sharded lm head all-gathers into the replicated carried logits
  exactly once per step. Greedy output is token-exact vs the single-chip
  engine.
* **Level 2 — data parallelism** (:class:`ReplicaRouter`): N
  :class:`~paddle_tpu.serving.AsyncLLMServer` replicas (each possibly a
  TP engine) behind one router that places every request by a score
  combining **load** (queue depth + running slots + KV-pool occupancy,
  read from each replica's existing Prometheus gauges) and **prefix
  affinity** (a read-only probe of each replica's content-hash store for
  the longest cached prefix of the incoming prompt — the replica that
  already holds the system prompt serves it with zero prefill FLOPs for
  the shared span). Placement falls back to least-loaded when nothing
  hits. Failover: a dead replica's QUEUED requests (nothing streamed
  yet) resubmit transparently to survivors; its IN-FLIGHT requests
  (tokens already streamed) fail with the attributable
  ``finish_reason="replica_lost"``. :meth:`ReplicaRouter.drain` removes
  a replica gracefully (migrate queued, finish running, stop).

Everything is testable end-to-end on CPU via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (the
``tests/conftest.py`` virtual-mesh pattern).

Scoring formula (documented contract, see docs/architecture.md)::

    score(replica) = affinity_weight * hit_tokens / prompt_len
                   - load_weight * ((queue_depth + engine_waiting
                                     + running_slots) / max_batch
                                    + kv_pool_occupancy)

highest score wins; ties break toward the lower replica index.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

import numpy as np

from ..analysis import lock_watchdog as _lockwatch
from .types import (ServeResult, ServerClosed, ServerQueueFull,
                    TraceContext)

__all__ = ["ReplicaRouter", "RouterHandle", "tp_serving_mesh",
           "shard_model_tp", "tp_engine", "FLEET_TAIL_CAUSES"]

#: every cause :meth:`ReplicaRouter.explain_tail` can name BEYOND the
#: per-replica :data:`~paddle_tpu.profiler.flight_recorder.TAIL_CAUSES`
#: taxonomy: a cross-replica boundary gap is either the migration
#: itself (``kv_ship:{phase}``, phase the dominant entry of
#: ``kv_transport.MIGRATION_PHASES`` — kept in lockstep by test +
#: PTL008) or a failover resubmission's re-prefill window. STRICT
#: registry, like TAIL_CAUSES/ALERT_KINDS.
FLEET_TAIL_CAUSES = ("failover_resubmit", "kv_ship:serialize",
                     "kv_ship:transport", "kv_ship:import",
                     "kv_ship:place", "kv_ship:stitch")


# ---------------------------------------------------------------------------
# Level 1 — tensor-parallel engine construction
# ---------------------------------------------------------------------------

def tp_serving_mesh(tp=None, devices=None):
    """A ``("tp",)`` jax Mesh over ``tp`` devices (default: all local
    devices). The axis NAME is the contract: ``LLMEngine(mesh=...)``
    shards its KV buffers iff the mesh carries a ``"tp"`` axis of size
    > 1 (any other mesh keeps the legacy replicated-buffer behavior)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if tp is not None:
        if len(devices) < tp:
            raise ValueError(f"need {tp} devices for tp={tp}, have "
                             f"{len(devices)}")
        devices = devices[:tp]
    return Mesh(np.asarray(devices), ("tp",))


def shard_model_tp(model, mesh, axis="tp"):
    """Lay the llama stack's weights out TP-sharded on ``mesh`` in place
    (Megatron placement via :func:`~paddle_tpu.models.llama.llama_tp_spec`:
    column-parallel q/k/v/gate/up + lm_head, row-parallel o/down, vocab-
    sharded embedding; norms replicated). Multi-process safe: every
    process must hold identical host values (same seed / same checkpoint)
    and contributes its addressable shards."""
    import jax
    from jax.sharding import NamedSharding

    from ..models.llama import llama_tp_spec

    for name, p in model.named_parameters():
        host = np.asarray(p._value)
        sharding = NamedSharding(mesh, llama_tp_spec(name, axis=axis))
        p._value = jax.make_array_from_callback(
            host.shape, sharding, lambda idx, h=host: h[idx])
    return model


def tp_engine(model, tp=None, mesh=None, devices=None, shard_weights=True,
              **engine_kw):
    """Build a tensor-parallel serving engine: shard ``model``'s weights
    over a ``("tp",)`` mesh (built from ``tp``/``devices`` unless a
    ``mesh`` is passed) and return ``LLMEngine(model, mesh=mesh, ...)``
    whose KV pools shard along kv-heads on the same axis. Token-exact
    greedy parity with the single-chip engine is the contract
    (tests/test_cluster.py asserts it for dense AND paged, prefix cache
    on and off). Engine kwargs pass through — including
    ``kv_cache_dtype="int8"|"int4"`` (quantized KV pools): the
    per-(block, head) scale arrays shard kv-heads with the pools and
    per-head absmax quantization is shard-local, so TP quantized
    serving stays token-exact vs single-chip quantized
    (tests/test_kv_quant.py::TestComposition::test_tp_mesh_exact)."""
    from ..inference import LLMEngine

    if mesh is None:
        mesh = tp_serving_mesh(tp, devices)
    if "tp" not in tuple(mesh.axis_names):
        raise ValueError(f"tp_engine needs a mesh with a 'tp' axis, got "
                         f"{tuple(mesh.axis_names)}")
    if shard_weights:
        shard_model_tp(model, mesh)
    return LLMEngine(model, mesh=mesh, **engine_kw)


# ---------------------------------------------------------------------------
# Level 2 — the data-parallel replica router
# ---------------------------------------------------------------------------

class RouterHandle:
    """Caller-side view of one routed request.

    Wraps the current replica-local
    :class:`~paddle_tpu.serving.RequestHandle` and survives failover: a
    queued request whose replica dies is transparently re-attached to a
    survivor (``resubmits`` counts the hops); a request that had already
    streamed tokens finishes with ``finish_reason="replica_lost"``.
    Iterate for the token stream, :meth:`result` for the terminal
    :class:`~paddle_tpu.serving.ServeResult` (its ``routing`` dict names
    the replica and the placement score that won)."""

    def __init__(self, router, prompt_ids, kwargs, routing_key=None):
        self._router = router
        self.prompt_ids = prompt_ids
        self._kwargs = kwargs
        self.routing_key = routing_key
        self._cond = threading.Condition()
        self._inner = None           # current replica-local RequestHandle
        self._replica = None
        self._final: ServeResult | None = None
        self._streamed = []          # tokens handed to the caller
        #: disaggregated serving state: {"budget": original
        #: max_new_tokens, "done": ship completed} on a request the
        #: router split into a prefill leg + decode leg; None otherwise
        self._disagg = None
        #: tokens committed on a finished prefill leg that the caller
        #: had NOT yet consumed when the ship migrated the stream — the
        #: decode replica treats them as resume prefix (never re-emits),
        #: so the router delivers them from here first
        self._carry = collections.deque()
        self._migrating = False      # drain: a cancel that must resubmit
        self.resubmits = 0
        #: failover-retry pacing: when every survivor's queue is full, a
        #: resubmission parks back in the outstanding set and retries on
        #: monitor ticks — with capped exponential backoff — until the
        #: router's retry window closes
        self._retry_since = None
        self._last_try = None
        self._retry_delay = router.poll_interval_s
        #: tokens the caller already consumed at failover time — the
        #: resume_inflight resubmission's continuation point
        self._resume_tokens = None

    @property
    def replica(self):
        """Index of the replica currently serving this request."""
        return self._replica

    @property
    def done(self):
        return self._final is not None

    @property
    def routing(self):
        """The routing/placement dict stamped on the current submission
        (also surfaced on the terminal ``ServeResult.routing``)."""
        inner = self._inner
        return inner.request.routing if inner is not None else None

    # -- router side -----------------------------------------------------
    def _attach(self, replica_idx, inner):
        with self._cond:
            self._inner = inner
            self._replica = replica_idx
            self._migrating = False
            self._cond.notify_all()

    def _finish(self, result):
        with self._cond:
            self._final = result
            self._cond.notify_all()

    # -- caller side -----------------------------------------------------
    def _pop_token(self):
        """Pop one streamed token AND record it in ``_streamed`` under
        the same lock — _resolve snapshots (pending deque, streamed
        list) under that lock too, so a crash result can never count a
        token in both."""
        if self._carry:
            # migrated-leg tokens the decode replica will never re-emit
            # (they ride resume_tokens): deliver them before the new
            # inner's stream
            try:
                tok = self._carry.popleft()
            except IndexError:
                tok = None
            if tok is not None:
                self._streamed.append(tok)
                return tok
        inner = self._inner
        if inner is None:
            return None
        with inner._cond:
            if inner._tokens:
                tok = inner._tokens.popleft()
                self._streamed.append(tok)
                return tok
        return None

    def tokens(self, timeout=None):
        """Generator over the token stream (across failover re-attach),
        with an optional per-token timeout."""
        while True:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while True:
                tok = self._pop_token()
                if tok is not None:
                    break
                if self._final is not None:
                    # re-pop: a token emitted between the miss above and
                    # the final landing must still be delivered
                    tok = self._pop_token()
                    if tok is None:
                        return
                    break
                inner = self._inner
                if inner is not None and inner.done:
                    # nudge the router — the waiting client drives the
                    # resolve latency, the monitor is only the backstop
                    self._router._resolve(self)
                    with self._cond:
                        if self._final is None:
                            self._cond.wait(0.02)
                elif inner is not None:
                    # the streaming hot path waits on the INNER handle's
                    # condition — _emit notifies it, so token delivery is
                    # notification-driven like a plain server handle (the
                    # bounded wait only exists to notice a failover
                    # re-attach swapping _inner out from under us)
                    with inner._cond:
                        if not inner._tokens and not inner.done:
                            inner._cond.wait(0.05)
                else:
                    with self._cond:
                        if self._final is None:
                            self._cond.wait(0.02)
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"routed request: no token within {timeout}s")
            yield tok

    def __iter__(self):
        return self.tokens()

    def result(self, timeout=None) -> ServeResult:
        """Block for the terminal result (post-failover if any)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cond:
                if self._final is not None:
                    return self._final
                inner = self._inner
            if inner is not None and inner.done:
                self._router._resolve(self)
                continue
            if inner is not None:
                try:
                    inner.result(timeout=0.05)
                    continue   # inner done: loop resolves it
                except TimeoutError:
                    pass
            else:
                with self._cond:
                    self._cond.wait(0.05)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"routed request not finished within {timeout}s")

    def cancel(self):
        inner = self._inner
        if inner is not None:
            inner.cancel()


class ReplicaRouter:
    """Load- and prefix-affinity-aware placement over N
    :class:`~paddle_tpu.serving.AsyncLLMServer` replicas.

    ``policy``: ``"affinity"`` (the default — affinity score on top of
    least-loaded), ``"least_loaded"`` (ignore affinity), or ``"random"``
    (the bench's control arm). ``submit(..., replica=i)`` pins a request
    explicitly (ops / tests). The router owns replica lifecycle when
    started through it: :meth:`start` starts un-started replicas plus the
    failover monitor, :meth:`stop` drains and stops everything.

    Failover contract: when a replica is LOST — its serving loop
    crashed terminally, or its :meth:`~AsyncLLMServer.health` probe
    reports ``"hung"`` (heartbeat stale past ``step_timeout_s``; the
    thread may still be alive) — every request it had QUEUED (nothing
    streamed yet) is resubmitted to a survivor and completes there
    (re-prefill reproduces the identical stream); every request already
    STREAMING fails with ``finish_reason="replica_lost"`` carrying the
    tokens streamed so far — or, with ``resume_inflight=True``,
    resubmits with ``resume_tokens`` and CONTINUES on the survivor
    (token-exactly for greedy; a sampled tail re-samples under the
    survivor's keys). A replica mid-supervised-restart (``"restarting"``) takes
    no new placements but keeps its residents: the resumption is about
    to happen locally. Nothing is silently dropped."""

    def __init__(self, replicas, affinity_weight=2.0, load_weight=1.0,
                 policy="affinity", poll_interval_s=0.01,
                 failover_retry_s=10.0, max_retry_backoff_s=0.5,
                 resume_inflight=False, seed=0,
                 adapter_affinity_weight=1.0, metrics_store=None,
                 metrics_interval_s=0.05, roles=None, transport=None,
                 pull_on_miss=False):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        if policy not in ("affinity", "least_loaded", "random"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.replicas = list(replicas)
        #: DISAGGREGATED prefill/decode serving (DistServe/Splitwise):
        #: ``roles={"prefill": [...], "decode": [...]}`` (replica
        #: indices). New generate prompts place on PREFILL replicas as a
        #: one-token leg with ``export_kv`` staging; on leg finish the
        #: router ships the staged entry to a DECODE replica (import +
        #: stitch re-admission, jumping the queue like a failover
        #: resume) and the stream continues there — token-exactly for
        #: greedy, and for sampled when the replicas share a
        #: ``sampling_seed``. Any ship/validation failure falls back to
        #: plain re-prefill on the decode side: shipping is an
        #: optimization, never a correctness dependency.
        if roles is not None:
            n = len(self.replicas)
            roles = {k: sorted(int(i) for i in v)
                     for k, v in roles.items()}
            for k in ("prefill", "decode"):
                if not roles.get(k):
                    raise ValueError(f"roles needs a non-empty {k!r} "
                                     f"replica list")
                if any(i < 0 or i >= n for i in roles[k]):
                    raise ValueError(f"roles[{k!r}] has an out-of-range "
                                     f"replica index (have {n})")
            # a migrated request keeps its rid across replicas (the
            # engine's restore + sampling keys validate by rid) — give
            # each replica a disjoint id base so a prefill-assigned rid
            # can never collide with a decode replica's own. 2**26
            # spacing: rids must stay int32-safe (the per-(rid,
            # position) sampling keys fold_in the rid), so 67M ids per
            # replica for up to 31 replicas
            if len(self.replicas) > 31:
                raise ValueError("disaggregated roles support at most "
                                 "31 replicas (int32 rid bases)")
            for i, srv in enumerate(self.replicas):
                srv._next_id = max(srv._next_id, i * (1 << 26))
        self.roles = roles
        #: staged-entry mover (serving.kv_transport): defaults to the
        #: in-process loopback, which still round-trips real serialized
        #: bytes. pull_on_miss additionally lets a replica whose prefix
        #: probe missed fetch the cached span from the peer that
        #: probe_prefix_len says can serve it, instead of recomputing.
        if transport is None and (roles is not None or pull_on_miss):
            from .kv_transport import InProcessTransport
            transport = InProcessTransport()
        self.transport = transport
        self.pull_on_miss = bool(pull_on_miss)
        #: end-to-end migration latency (leg finish → decode-side
        #: re-admission granted), observed per successful ship
        from ..profiler.serving_telemetry import LatencyHistogram
        self.migration_latency = LatencyHistogram()
        #: the same latency DECOMPOSED per kv_transport.MIGRATION_PHASES
        #: name — serialize/transport/import timed inside the
        #: transport's ship(), place around the decode-side placement,
        #: stitch read back from the destination engine's fenced
        #: restore. One histogram per phase; snapshot() surfaces them
        #: next to migration_latency.
        self.migration_phases = {}
        #: per-migration records (trace_id, rid, src→dst, perf_counter
        #: t0/t1, phase seconds, wire bytes) — bounded; feeds the merged
        #: trace's router lane and explain_tail's boundary-gap
        #: attribution
        self._migrations = collections.deque(maxlen=256)
        self.affinity_weight = float(affinity_weight)
        #: adapter-affinity bonus (multi-tenant serving): a replica
        #: whose adapter device cache already HOLDS the request's
        #: adapter serves it without a swap-in, so placement prefers it
        #: — scored as a flat bonus on top of the prefix/load formula
        #: (the swap cost is per-admission, not per-token)
        self.adapter_affinity_weight = float(adapter_affinity_weight)
        self.load_weight = float(load_weight)
        self.policy = policy
        self.poll_interval_s = float(poll_interval_s)
        #: how long a failover resubmission keeps retrying when every
        #: survivor's queue is full before the request fails as
        #: replica_lost — transient backpressure must not drop requests.
        #: Retries pace with CAPPED EXPONENTIAL BACKOFF: the delay
        #: doubles from poll_interval_s up to max_retry_backoff_s, so a
        #: long backpressure window costs O(log) placement passes, not a
        #: hot retry loop per parked handle.
        self.failover_retry_s = float(failover_retry_s)
        self.max_retry_backoff_s = float(max_retry_backoff_s)
        #: upgrade the failover contract for IN-FLIGHT requests: instead
        #: of failing with ``replica_lost``, resubmit them to a survivor
        #: with ``resume_tokens`` = everything the caller has consumed,
        #: so the stream CONTINUES — token-exactly for GREEDY requests
        #: (deterministic decode off the identical prefix). A SAMPLED
        #: stream continues from the consumed prefix but re-samples its
        #: tail under the survivor's own keys (fresh rid + fresh base
        #: key): distribution-correct, not bit-exact — unlike
        #: same-server supervised restart, which IS sampled-exact
        #: (same engine base key, same rid, per-position fold_in).
        #: Opt-in: resumption recomputes the undelivered tokens, which
        #: costs survivor FLOPs a latency-critical cluster may prefer to
        #: spend on fresh traffic.
        self.resume_inflight = bool(resume_inflight)
        #: optional router-level metrics store: the monitor loop feeds
        #: its own view (outstanding placements per replica, failover
        #: counters) as replica-labeled time series — the fleet-side
        #: half of the sensor layer (True = default-sized store)
        if metrics_store is True:
            from ..profiler.metrics_store import MetricsStore
            metrics_store = MetricsStore()
        # falsy (False) normalizes to the detached None off-path
        self.metrics_store = metrics_store or None
        #: monitor-side feed throttle (same discipline as the server's
        #: _feed_sensors): the monitor ticks every poll_interval_s
        #: (10ms default) but the store samples at this cadence
        self.metrics_interval_s = float(metrics_interval_s)
        self._ms_last_t = 0.0
        self._rng = np.random.default_rng(seed)
        # PADDLE_TPU_LOCK_CHECKS=1: acquisition edges feed the PTL004
        # lock-order watchdog (paddle_tpu.analysis.lock_watchdog)
        self._lock = _lockwatch.tracked(threading.Lock(),
                                        "ReplicaRouter._lock")
        self._outstanding: set[RouterHandle] = set()
        #: outstanding placements per replica, counted by the ROUTER at
        #: placement time — the load gauges are sampled by each replica's
        #: serve loop and lag a burst of submissions, so a salvo would
        #: otherwise pile onto whichever replica scored best a
        #: millisecond ago. The score uses max(gauges, this).
        self._live_per = [0] * len(self.replicas)
        self._draining: set[int] = set()
        self._stop_evt = threading.Event()
        self._monitor = None
        self.stats = {"submitted": 0, "affinity_routed": 0,
                      "adapter_routed": 0,
                      "resubmitted": 0, "replica_lost": 0,
                      "resumed": 0, "evicted_hung": 0,
                      #: failover resubmissions whose request was
                      #: SWAP-RESIDENT on the lost replica (its KV lived
                      #: in that host's RAM tier, awaiting re-admission)
                      #: — every streamed token is already with the
                      #: caller, so resumption is exact and the host
                      #: copy is simply abandoned with the replica
                      "swap_resident_failover": 0,
                      #: disaggregated serving: prefill legs whose KV
                      #: shipped to a decode replica (stitch-only
                      #: re-admission), legs that fell back to plain
                      #: re-prefill (ship/import/validation failure),
                      #: host-resident KV abandoned by a hung-/dead-
                      #: replica failover (swap-resident or mid-ship —
                      #: transfer work the fleet paid and lost), and
                      #: prefix blocks fetched from peers on a probe
                      #: miss instead of recomputed
                      "kv_shipped": 0, "kv_ship_fallback": 0,
                      "kv_ship_abandoned": 0, "pull_on_miss_blocks": 0,
                      "placements": [0] * len(self.replicas)}

    # -- lifecycle -------------------------------------------------------
    def start(self):
        for srv in self.replicas:
            if srv._thread is None:
                srv.start()
        self._stop_evt.clear()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="paddle-tpu-router",
                                         daemon=True)
        self._monitor.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Stop the monitor and every replica. A replica whose stop
        fails — a crashed loop re-raising, or a TimeoutError from a
        join still inside a long compile — is collected, not fatal, so
        one bad replica can't wedge cluster shutdown. Returns the
        ``[(replica_idx, exception), ...]`` list."""
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout)
            self._monitor = None
        errors = []
        for i, srv in enumerate(self.replicas):
            try:
                srv.stop(drain=drain, timeout=timeout)
            except Exception as e:   # noqa: BLE001 — collect, keep going
                errors.append((i, e))
        return errors

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=exc == (None, None, None))
        return False

    def alive(self, idx):
        srv = self.replicas[idx]
        return (srv._thread is not None and srv._thread.is_alive()
                and srv._crashed is None and srv._accepting)

    def healthy(self, idx):
        """Placement eligibility: thread-level liveness AND the health
        protocol's verdict. A ``"hung"`` replica (thread alive, heartbeat
        stale past its ``step_timeout_s``) takes no new placements and
        its residents fail over; a ``"restarting"`` one (supervised
        recovery between crash and re-arm) takes no new placements but
        its residents stay PUT — the resumption is about to happen."""
        if not self.alive(idx):
            return False
        try:
            return self.replicas[idx].health()["state"] == "running"
        except Exception:   # routing heuristic: never let it fail
            return True

    # -- placement -------------------------------------------------------
    def _score(self, idx, ids, hashes=None, adapter_id=0):
        """(score, affinity_tokens, adapter_hit) of placing ``ids`` on
        replica ``idx`` — the documented formula (module docstring) plus
        the ADAPTER-affinity bonus: a replica whose adapter cache
        already holds ``adapter_id`` serves without a swap-in.
        ``hashes``: precomputed chain hashes (the hash chain depends on
        token content + tenant only, so one computation serves every
        same-block_size replica)."""
        srv = self.replicas[idx]
        aff = 0
        adapter_hit = False
        if self.policy == "affinity":
            try:
                aff = int(srv.engine.probe_prefix_len(
                    ids, chain_hashes=hashes, adapter_id=adapter_id))
            except Exception:   # routing heuristic: never let it fail
                aff = 0
            if adapter_id:
                try:
                    adapter_hit = bool(
                        srv.engine.adapter_resident(adapter_id))
                except Exception:
                    adapter_hit = False
        g = srv.telemetry.get_gauges()
        load = (g.get("queue_depth", 0.0) + g.get("engine_waiting", 0.0)
                + g.get("running_slots", 0.0)) / max(srv.engine.B, 1)
        # the router's own outstanding count covers the gauge lag window
        # (submissions placed this millisecond that no loop pass has
        # sampled yet); max() rather than + because settled placements
        # appear in both views
        load = max(load, self._live_per[idx] / max(srv.engine.B, 1))
        # pool pressure counts only UNAVAILABLE blocks: the raw occupancy
        # gauge treats LRU-cached (evictable) prefix blocks as occupied,
        # which would permanently penalize exactly the warm replica the
        # affinity term is trying to prefer
        pool = g.get("kv_pool_occupancy", 0.0)
        cached = g.get("prefix_cached_blocks", 0.0)
        n_blocks = getattr(srv.engine, "n_blocks", 0)
        if n_blocks:
            pool = max(0.0, pool - cached / n_blocks)
        score = self.affinity_weight * (aff / max(len(ids), 1)) \
            + self.adapter_affinity_weight * float(adapter_hit) \
            - self.load_weight * (load + pool)
        return score, aff, adapter_hit

    def _role_for(self, handle):
        """Which role set a submission places into, or None (no
        disaggregation). A split request's DECODE leg (ship done — it
        carries a resume prefix) goes to decode replicas; everything
        else — fresh prompts, prefill legs retrying after a failed
        replica, embeds — is prefill-heavy work and goes to prefill
        replicas."""
        if self.roles is None:
            return None
        d = handle._disagg
        return "decode" if (d is not None and d.get("shipping")) \
            else "prefill"

    def _rank(self, ids, pin=None, adapter_id=0, role=None):
        """Candidate replicas best-first as (idx, score, aff_tokens,
        adapter_hit). ``role``: restrict candidates to that role set
        (disaggregated serving) — degrading gracefully to EVERY healthy
        replica when the whole role set is down, so losing the last
        prefill replica converts prompts to mixed placement instead of
        request loss."""
        #: prompt hash chain per (block_size, tenant) — computed at most
        #: once per submission, shared by same-geometry replicas' probes
        hash_cache = {}

        def hashes_for(idx):
            eng = self.replicas[idx].engine
            if self.policy != "affinity" or \
                    getattr(eng, "prefix_cache", False) is False:
                return None
            bs = eng.block_size
            key = (bs, adapter_id)
            if key not in hash_cache:
                hash_cache[key] = eng.prefix_chain_hashes(
                    ids, adapter_id=adapter_id)
            return hash_cache[key]

        if pin is not None:
            score, aff, ahit = self._score(pin, ids, hashes_for(pin),
                                           adapter_id)
            return [(pin, score, aff, ahit)]
        cand = [i for i in range(len(self.replicas))
                if self.healthy(i) and i not in self._draining]
        if role is not None and self.roles is not None:
            in_role = [i for i in cand if i in self.roles[role]]
            cand = in_role or cand
        if not cand:
            return []
        if self.policy == "random":
            order = [int(i) for i in self._rng.permutation(cand)]
            return [(i, 0.0, 0, False) for i in order]
        scored = [(i,) + self._score(i, ids, hashes_for(i), adapter_id)
                  for i in cand]
        scored.sort(key=lambda t: (-t[1], t[0]))
        return scored

    # -- submission ------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=64, temperature=0.0,
               top_p=1.0, eos_token_id=None, deadline_s=None,
               routing_key=None, replica=None, block=True,
               timeout=None, readout_stride=None, adapter_id=0,
               kind="generate") -> RouterHandle:
        """Place and submit one request; returns its
        :class:`RouterHandle`. ``routing_key`` is an opaque caller tag
        that rides the placement dict into ``ServeResult.routing`` and
        the request's trace spans. ``replica`` pins placement (skips
        scoring). ``readout_stride`` is the per-request latency-tier
        pin, forwarded to whichever replica serves (and re-serves, on
        failover) the request. Backpressure: a replica whose queue is
        full is skipped for the next-best; with every queue full,
        blocks (``block=True``, up to ``timeout``) or raises
        :class:`~paddle_tpu.serving.ServerQueueFull`."""
        ids = np.asarray(
            prompt_ids.numpy() if hasattr(prompt_ids, "numpy")
            else prompt_ids, dtype=np.int32).reshape(-1)
        kwargs = dict(max_new_tokens=max_new_tokens,
                      temperature=temperature, top_p=top_p,
                      eos_token_id=eos_token_id, deadline_s=deadline_s,
                      readout_stride=readout_stride,
                      adapter_id=adapter_id, kind=kind,
                      # fleet-entry trace mint: rides _kwargs so EVERY
                      # resubmission hop (ship / failover / queue retry)
                      # carries the same trace_id; the hop-bump sites
                      # replace it with child contexts
                      trace_ctx=TraceContext.mint("router"))
        handle = RouterHandle(self, ids, kwargs, routing_key)
        if self.roles is not None and kind == "generate" and \
                int(max_new_tokens) > 1:
            # disaggregated split: submit a ONE-token prefill leg with
            # export staging; the leg's finish hook ships the KV and
            # resubmits the remaining budget on a decode replica (an
            # eos on the very first token just finishes normally). A
            # budget of 1 is pure prefill already — no split.
            handle._disagg = {"budget": int(max_new_tokens)}
            kwargs["max_new_tokens"] = 1
            kwargs["export_kv"] = True
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = self.poll_interval_s
        while True:
            err = self._try_place(handle, ids, pin=replica)
            if err is None:
                return handle
            # a validation rejection is the caller's bug, not transient
            # backpressure — surface it synchronously like a plain
            # server's submit() would, never retry it
            if not block or isinstance(err, (ServerClosed, ValueError)):
                raise err
            if deadline is not None and time.monotonic() > deadline:
                raise err
            # capped exponential backoff: sustained backpressure must
            # not melt into a hot scoring/placement spin per submitter
            time.sleep(delay)
            delay = min(delay * 2.0, self.max_retry_backoff_s)

    def _try_place(self, handle, ids, pin=None, resubmit=False):
        """One placement pass over the ranked candidates. Returns None
        on success, else the error to surface (queue-full everywhere /
        no replica alive). Scoring (affinity probes hash the whole
        prompt per replica) runs OUTSIDE the router lock — scores are an
        advisory heuristic over point-in-time reads, so concurrent
        submitters may score stale-ish state but must not serialize on
        each other's hash walks; the lock guards only the actual
        placement bookkeeping."""
        adapter_id = int(handle._kwargs.get("adapter_id") or 0)
        ranked = self._rank(ids, pin=pin, adapter_id=adapter_id,
                            role=self._role_for(handle))
        if self.pull_on_miss and ranked and \
                handle._kwargs.get("kind", "generate") == "generate":
            # BEFORE the submit: the fetched span must be in the target's
            # spill inbox before its engine thread runs this request's
            # admission probe (the inbox drains at the top of the next
            # step, ahead of admission)
            self._pull_prefix(ranked[0], ids, adapter_id)
        with self._lock:
            last_err = None
            for idx, score, aff, ahit in ranked:
                srv = self.replicas[idx]
                routing = {"replica": idx, "policy": self.policy,
                           "score": round(float(score), 4),
                           "affinity_tokens": int(aff),
                           # the handle's counter increments only once
                           # this placement SUCCEEDS — stamp what this
                           # submission will be, not what the last was
                           "resubmits": handle.resubmits
                           + (1 if resubmit else 0)}
                if adapter_id:
                    routing["adapter_id"] = adapter_id
                    routing["adapter_resident"] = bool(ahit)
                if handle.routing_key is not None:
                    routing["routing_key"] = handle.routing_key
                try:
                    inner = srv.submit(ids, routing=routing, block=False,
                                       resume_tokens=handle._resume_tokens
                                       or None, **handle._kwargs)
                except (ServerQueueFull, ServerClosed, ValueError) as e:
                    # ValueError: this replica's validation rejected the
                    # prompt (e.g. prompt⊕resume at ITS capacity edge) —
                    # a differently-sized survivor may still take it; an
                    # uncaught raise here would kill the monitor thread
                    # mid-failover
                    last_err = e
                    continue
                handle._attach(idx, inner)
                self._outstanding.add(handle)
                self._live_per[idx] += 1
                self.stats["placements"][idx] += 1
                if not resubmit:
                    self.stats["submitted"] += 1
                    if aff > 0:
                        self.stats["affinity_routed"] += 1
                    if adapter_id and ahit:
                        self.stats["adapter_routed"] += 1
                return None
            return last_err or ServerClosed("no replica alive")

    def _pull_prefix(self, top, ids, adapter_id):
        """Pull-on-miss: when the chosen replica's prefix probe (device
        content store + its own spill store) covers LESS of this prompt
        than some peer could serve, fetch the missing span's blocks
        from that peer over the transport instead of recomputing them.
        Entirely best-effort and read-only on the peer: a block evicted
        mid-gather just truncates the span, and the target re-derives
        every chain hash before registering, so a bad fetch can never
        corrupt the content store. Requires the target to run an armed
        spill store (``kv_host_spill_bytes > 0``) — the fetched blocks
        land there and the existing probe → promote path serves them."""
        idx, _, aff, _ = top
        eng = self.replicas[idx].engine
        if self.transport is None or \
                not getattr(eng, "prefix_cache", False) or \
                not getattr(eng, "kv_host_spill_bytes", 0):
            return
        bs = eng.block_size
        try:
            hashes = eng.prefix_chain_hashes(ids, adapter_id=adapter_id)
        except Exception:
            return
        have = int(aff) // bs
        if have >= len(hashes):
            return
        want = hashes[have:]
        best_peer, best_len = None, 0
        for j, srv in enumerate(self.replicas):
            if j == idx or not self.healthy(j):
                continue
            peng = srv.engine
            if getattr(peng, "block_size", None) != bs or \
                    getattr(peng, "kv_quant", None) != eng.kv_quant:
                continue
            try:
                plen = int(peng.probe_prefix_len(
                    ids, chain_hashes=hashes, adapter_id=adapter_id))
            except Exception:
                continue
            if plen // bs > have and plen > best_len:
                best_peer, best_len = peng, plen
        if best_peer is None:
            return
        try:
            entries = best_peer.export_prefix_blocks(want)
            if not entries:
                return
            n, _ = self.transport.ship_prefix_blocks(entries, eng)
        except Exception:
            return
        if n:
            with self._lock:
                self.stats["pull_on_miss_blocks"] += n

    def num_outstanding(self):
        with self._lock:
            return len(self._outstanding)

    # -- failover / resolution -------------------------------------------
    def _bump_trace(self, handle, via):
        """Advance the handle's trace context one hop: same trace_id,
        hop+1, parented on the previous hop's span — called once per
        resubmission EPISODE (ship, failover, first queue-full park),
        never per retry tick, so hop counts hops, not backoff spins."""
        tc = TraceContext.coerce(handle._kwargs.get("trace_ctx"))
        if tc is not None:
            handle._kwargs["trace_ctx"] = tc.child(via)

    def _done_with(self, handle):
        """Drop a handle from the outstanding set + the per-replica
        placement count (CALLER HOLDS self._lock)."""
        if handle in self._outstanding:
            self._outstanding.discard(handle)
            if handle._replica is not None:
                self._live_per[handle._replica] -= 1

    def _monitor_loop(self):
        while not self._stop_evt.wait(self.poll_interval_s):
            with self._lock:
                handles = list(self._outstanding)
            for rh in handles:
                inner = rh._inner
                if inner is not None and inner.done:
                    self._resolve(rh)
            self._failover_hung()
            if self.metrics_store is not None:
                self._feed_metrics_store()

    def _feed_metrics_store(self):
        """Feed the router's own counters + per-replica placement view
        into the router-level metrics store (interval-throttled — the
        monitor ticks far faster than a useful sampling cadence)."""
        store = self.metrics_store
        t = time.monotonic()
        if t - self._ms_last_t < self.metrics_interval_s:
            return
        self._ms_last_t = t
        with self._lock:
            live = list(self._live_per)
            stats = dict(self.stats)
        store.observe("router_outstanding", sum(live), t=t)
        for i, n in enumerate(live):
            store.observe("router_replica_outstanding", n, t=t,
                          replica=i)
        for key in ("submitted", "resubmitted", "replica_lost",
                    "resumed", "evicted_hung"):
            store.observe(f"router_{key}", stats[key], t=t)

    def _failover_hung(self):
        """Health-probe failover: a replica whose :meth:`AsyncLLMServer
        .health` verdict is ``"hung"`` (heartbeat stale past its
        ``step_timeout_s`` — the loop thread is ALIVE but stuck inside a
        step) gets its resident requests evicted and failed over NOW,
        without waiting for the thread to die. ``evict_request`` detaches
        each handle from the wedged server (a later revival decodes into
        dropped outputs, never into a double delivery), and the normal
        resolve path converts the eviction into resubmission — with
        ``resume_inflight``, stream continuation (greedy-exact)."""
        for idx, srv in enumerate(self.replicas):
            try:
                hung = srv.health()["state"] == "hung"
            except Exception:
                hung = False
            if not hung:
                continue
            # swap-resident awareness: requests whose KV the wedged
            # replica had demoted to ITS host tier are not in any slot,
            # but they are exactly as resumable as running ones — the
            # committed tokens all streamed before the preemption that
            # swapped them out. The probe is read-only dict access on
            # the (stuck, not racing) engine thread's state.
            try:
                swap_rids = set(srv.engine.swap_resident_rids())
            except Exception:
                swap_rids = set()
            with self._lock:
                mine = [rh for rh in self._outstanding
                        if rh._replica == idx and not rh.done]
            for rh in mine:
                inner = rh._inner
                if inner is not None and not inner.done:
                    if srv.evict_request(inner.request_id,
                                         reason="replica_lost") is not None:
                        with self._lock:
                            self.stats["evicted_hung"] += 1
                            if inner.request_id in swap_rids:
                                self.stats["swap_resident_failover"] += 1
                                # the wedged replica's host-resident KV
                                # copy is abandoned with it — transfer
                                # work the fleet paid and lost
                                self.stats["kv_ship_abandoned"] += 1
                self._resolve(rh)

    def _resolve(self, handle):
        """Turn a finished replica-local result into the routed
        request's fate: final result, or failover resubmission.
        Idempotent AND race-safe: the monitor and any number of waiting
        callers may resolve concurrently — membership in the
        outstanding set (removed atomically under the router lock) is
        the gate, so exactly one caller acts."""
        inner = handle._inner
        if inner is None or not inner.done or handle.done:
            return
        res = inner.result_obj
        reason = res.finish_reason or ""
        #: "lost" covers both shapes of replica loss: a terminal serve-
        #: loop crash (server_error) and a hung-replica eviction
        #: (replica_lost via evict_request) — either way the replica
        #: cannot finish this request
        lost = reason.startswith("server_error") or \
            reason == "replica_lost"
        migrating = handle._migrating and reason == "cancelled"
        streamed = inner.first_token_at is not None
        d = handle._disagg
        if d is not None and not lost and not migrating and \
                reason == "length" and not d.get("placed"):
            # the PREFILL-COMPLETE hook: the leg hit its one-token
            # budget with the real budget unspent — ship the staged KV
            # and continue on a decode replica
            self._ship_and_resubmit(handle, inner, res)
            return
        if d is not None and lost and not d.get("placed") and \
                not d.get("abandoned"):
            # the prefill leg's replica died with the staged/committed
            # KV still on it (mid-ship): the transfer work is lost —
            # make it visible before the plain failover path re-prefills
            d["abandoned"] = True
            with self._lock:
                self.stats["kv_ship_abandoned"] += 1
        # in-flight resumption (opt-in): resubmit with resume_tokens =
        # everything the caller consumed, so the stream continues
        # token-exactly on a survivor instead of failing replica_lost
        resume_stream = lost and streamed and self.resume_inflight
        # a drain-migration that raced its cancel against the first
        # token must NOT resubmit (the caller may already have consumed
        # tokens a fresh greedy stream would repeat) — the cancel stands
        resubmit = (lost and not streamed) or resume_stream or \
            (migrating and not streamed and not handle._streamed)
        now = time.monotonic()
        if resubmit and handle._last_try is not None and \
                now - handle._last_try < handle._retry_delay:
            # pacing: a queue-full retry parked the handle; wait out its
            # current backoff delay instead of hot-spinning the
            # placement pass from every blocked caller
            return
        with self._lock:
            if handle not in self._outstanding:
                return          # another caller won the resolve
            self._done_with(handle)
            if resubmit:
                handle._replica = None   # no live placement while parked
            if lost and streamed and not resume_stream:
                self.stats["replica_lost"] += 1
        if not lost and not migrating:
            handle._finish(res)
            return
        if not resubmit:
            if lost:
                # in-flight: tokens already left the building — fail
                # attributably, carrying everything streamed so far
                # (handed-out tokens plus any still in the deque —
                # snapshot under the same lock _pop_token records with,
                # so no token lands in both lists)
                with inner._cond:
                    pending = list(inner._tokens)
                    emitted = list(handle._streamed)
                handle._finish(ServeResult(
                    res.request_id, emitted + pending,
                    "replica_lost", True, routing=inner.request.routing,
                    trace_ctx=res.trace_ctx or inner.request.trace_ctx))
            else:
                handle._finish(res)
            return
        if resume_stream:
            # freeze the dead stream: clear the undelivered deque under
            # the pop lock so a racing caller can't consume a token the
            # survivor is about to recompute, then resume from exactly
            # what the caller HAS seen
            with inner._cond:
                inner._tokens.clear()
                handle._resume_tokens = list(handle._streamed)
        if resubmit:
            # carry the dead replica's learned draft-acceptance EWMA to
            # the survivor (speculative engines): the resumed stream's
            # verify-k grants start at the adapted window, like the
            # readout_stride pin rides _kwargs. Host-dict read off the
            # dead server's engine — safe from this thread, best-effort.
            try:
                ewma = inner._server.engine.spec_ewma_for(
                    inner.request_id)
            except Exception:
                ewma = None
            if ewma is not None:
                handle._kwargs["spec_ewma"] = ewma
        # resubmit to a survivor (placement excludes the dead/hung/
        # draining replica via healthy()/draining checks)
        if handle._retry_since is None:
            # first attempt of this failover episode — parked queue-full
            # retries keep the already-bumped context
            self._bump_trace(handle, "failover")
        handle._last_try = now
        err = self._try_place(handle, handle.prompt_ids, resubmit=True)
        if err is None:
            handle.resubmits += 1
            handle._retry_since = None
            handle._retry_delay = self.poll_interval_s
            with self._lock:
                self.stats["resubmitted"] += 1
                if resume_stream:
                    self.stats["resumed"] += 1
            return
        if isinstance(err, ServerQueueFull) and not self._stop_evt.is_set():
            # transient backpressure on the survivors: park the handle
            # back in the outstanding set — the monitor's next tick
            # retries, the delay doubling up to max_retry_backoff_s —
            # until the failover window closes. Dropping it NOW would
            # convert a momentarily full queue into request loss.
            if handle._retry_since is None:
                handle._retry_since = now
                self._bump_trace(handle, "queue_retry")
            if now - handle._retry_since < self.failover_retry_s:
                handle._retry_delay = min(handle._retry_delay * 2.0,
                                          self.max_retry_backoff_s)
                with self._lock:
                    self._outstanding.add(handle)
                return
        with self._lock:
            self.stats["replica_lost"] += 1
        handle._finish(ServeResult(
            res.request_id,
            # a lost replica's terminal result already carries the full
            # emitted stream (resume prefix included); a failed drain
            # migration only ever handed out what the caller consumed
            list(res.token_ids) if lost else list(handle._streamed),
            "replica_lost", True, routing=inner.request.routing,
            trace_ctx=res.trace_ctx or inner.request.trace_ctx))

    def _ship_and_resubmit(self, handle, inner, res):
        """The prefill-complete hook (disaggregated serving): export
        the finished leg's staged KV, ship it over the transport to the
        best decode replica, and resubmit the remaining budget there
        under the SAME rid with the leg's tokens as resume prefix — the
        decode engine's swap-store restore re-admits with the one-token
        stitch (``AdmissionQueue.put(front=...)`` grant, like a
        failover resume), so the migrated request pays ZERO re-prefill
        tokens. ANY failure — export raced the store cap, transport or
        pool-geometry reject, validation, queue full on the shipped-to
        replica — falls back to plain resume resubmission (re-prefill
        on the decode side, token-identical stream). Re-entrant: a
        queue-full park retries from the monitor with the staged entry
        cached on the handle, paced by the failover backoff."""
        now = time.monotonic()
        if handle._last_try is not None and \
                now - handle._last_try < handle._retry_delay:
            return                   # parked: wait out the backoff
        with self._lock:
            if handle not in self._outstanding:
                return               # another caller won the resolve
            self._done_with(handle)
            handle._replica = None
        t0 = time.perf_counter()
        d = handle._disagg
        if not d.get("shipping"):
            # first ship attempt of this migration (parked retries keep
            # the already-bumped context): the decode leg is hop+1
            self._bump_trace(handle, "kv_ship")
        d["shipping"] = True         # role flips to "decode" from here
        src = inner._server
        src_idx = next((i for i, s in enumerate(self.replicas)
                        if s is src), None)
        rid = inner.request_id
        # freeze the leg's stream: undelivered tokens move to the
        # router-level carry (the decode replica treats the WHOLE leg
        # stream as resume prefix and never re-emits it)
        with inner._cond:
            pending = list(inner._tokens)
            inner._tokens.clear()
        handle._carry.extend(pending)
        leg_tokens = [int(t) for t in res.token_ids]
        handle._resume_tokens = leg_tokens
        handle._kwargs["max_new_tokens"] = d["budget"]
        handle._kwargs["export_kv"] = False
        # the rid is the migration's identity: the decode engine's
        # restore validates by it, and the shared-sampling_seed
        # per-(rid, position) keys make a SAMPLED continuation
        # token-exact only under the same rid
        handle._kwargs["request_id"] = rid
        if "entry" not in d:
            try:
                te0 = time.perf_counter()
                d["entry"] = src.engine.export_kv(rid)
                # the source-side export is part of the migration's
                # serialize cost (gathering the KV into the staged
                # entry) — folded into the serialize phase below so the
                # phase sub-spans account for the latency window
                d["export_s"] = time.perf_counter() - te0
            except Exception:
                d["entry"] = None
        entry = d["entry"]
        full_ids = np.concatenate(
            [np.asarray(handle.prompt_ids, np.int32),
             np.asarray(leg_tokens, np.int32)])
        adapter_id = int(handle._kwargs.get("adapter_id") or 0)
        ranked = self._rank(full_ids, adapter_id=adapter_id,
                            role="decode")
        shipped = False
        err = ServerClosed("no replica alive")
        phases, nbytes, dst_idx = {}, 0, None
        for idx, _score, _aff, _ahit in ranked:
            dst = self.replicas[idx]
            shipped = False
            phases, nbytes, dst_idx = {}, 0, idx
            if entry is not None and self.transport is not None:
                try:
                    # the transport times its own phases (serialize/
                    # transport/import) and returns them per call, so
                    # concurrent ships can't clobber each other
                    nbytes, tphases = self.transport.ship(
                        entry, dst.engine)
                    shipped = True
                    phases = dict(tphases or {})
                    if "serialize" in phases:
                        phases["serialize"] += d.get("export_s", 0.0)
                except Exception:
                    shipped = False
            tp0 = time.perf_counter()
            err = self._try_place(handle, handle.prompt_ids, pin=idx,
                                  resubmit=True)
            if err is None:
                if shipped:
                    phases["place"] = time.perf_counter() - tp0
                break
            if shipped:
                # placement failed AFTER the import landed: pop the
                # orphaned staged entry (GIL-atomic) so it cannot
                # linger under a rid this replica never admits
                try:
                    dst.engine._swap_store.pop(rid, None)
                except Exception:
                    pass
                shipped = False
        if err is None:
            d["placed"] = True
            handle.resubmits += 1
            handle._retry_since = None
            handle._retry_delay = self.poll_interval_s
            handle._last_try = None
            t1 = time.perf_counter()
            self.migration_latency.observe(t1 - t0)
            if shipped:
                for p, v in phases.items():
                    self._observe_phase(p, v)
                tc = TraceContext.coerce(
                    handle._kwargs.get("trace_ctx"))
                with self._lock:
                    # stitch is timed DESTINATION-side (the fenced
                    # restore at re-admission, after this returns) —
                    # _finalize_migrations reads it back off the decode
                    # engine before anyone consumes the record
                    self._migrations.append({
                        "trace_id": tc.trace_id if tc else None,
                        "rid": rid, "src": src_idx,
                        "dst": dst_idx, "t0": t0, "t1": t1,
                        "phases": phases, "bytes": int(nbytes)})
            with self._lock:
                self.stats["resubmitted"] += 1
                if shipped:
                    self.stats["kv_shipped"] += 1
                else:
                    self.stats["kv_ship_fallback"] += 1
            return
        if isinstance(err, ServerQueueFull) and \
                not self._stop_evt.is_set():
            # transient decode-side backpressure: park and retry from
            # the monitor, exactly like a failover resubmission
            if handle._retry_since is None:
                handle._retry_since = now
                self._bump_trace(handle, "queue_retry")
            if now - handle._retry_since < self.failover_retry_s:
                handle._last_try = now
                handle._retry_delay = min(handle._retry_delay * 2.0,
                                          self.max_retry_backoff_s)
                with self._lock:
                    self._outstanding.add(handle)
                return
        # terminal: the retry window closed or no replica can take it
        with self._lock:
            self.stats["replica_lost"] += 1
            self.stats["kv_ship_fallback"] += 1
        handle._finish(ServeResult(
            res.request_id, list(res.token_ids), "replica_lost", True,
            routing=inner.request.routing,
            trace_ctx=res.trace_ctx or inner.request.trace_ctx))

    # -- migration phase bookkeeping -------------------------------------
    def _observe_phase(self, phase, seconds):
        """Book one migration phase observation (histograms created on
        first use, keyed by kv_transport.MIGRATION_PHASES names)."""
        from ..profiler.serving_telemetry import LatencyHistogram
        h = self.migration_phases.get(phase)
        if h is None:
            h = self.migration_phases[phase] = LatencyHistogram()
        h.observe(seconds)

    def _finalize_migrations(self):
        """Fill in each migration record's destination-side ``stitch``
        wall — timed by the decode engine's fenced restore AFTER the
        ship returned, so it's read back lazily here — and book it,
        once, into the phase histograms. Returns the records, oldest
        first."""
        with self._lock:
            migs = list(self._migrations)
        for m in migs:
            if "stitch" not in m["phases"] and m["dst"] is not None:
                eng = self.replicas[m["dst"]].engine
                s = getattr(eng, "_stitch_s", {}).get(m["rid"])
                if s is not None:
                    m["phases"]["stitch"] = s
                    self._observe_phase("stitch", s)
        return migs

    # -- drain -----------------------------------------------------------
    def drain(self, idx, timeout=30.0):
        """Gracefully remove replica ``idx``: stop placing new work on
        it, migrate its queued (nothing-streamed) requests to survivors,
        let its running requests finish, then stop it. The replica stays
        in ``replicas`` (stopped) so indices remain stable."""
        with self._lock:
            self._draining.add(idx)
            srv = self.replicas[idx]
            mine = [rh for rh in self._outstanding
                    if rh._replica == idx and not rh.done]
        for rh in mine:
            inner = rh._inner
            if inner is not None and inner.first_token_at is None:
                rh._migrating = True
                inner.cancel()
        deadline = time.monotonic() + timeout
        while any(rh._migrating and not rh.done for rh in mine):
            if time.monotonic() > deadline:
                raise TimeoutError(f"drain({idx}): migrations incomplete "
                                   f"after {timeout}s")
            for rh in mine:
                inner = rh._inner
                if rh._migrating and inner is not None and inner.done:
                    self._resolve(rh)
            time.sleep(self.poll_interval_s)
        srv.stop(drain=True, timeout=max(deadline - time.monotonic(), 0.1))

    # -- observability ---------------------------------------------------
    def snapshot(self):
        """JSON-ready cluster view: router stats + each replica's
        telemetry snapshot (keyed by replica index)."""
        with self._lock:
            out = {"policy": self.policy,
                   "stats": {k: (list(v) if isinstance(v, list) else v)
                             for k, v in self.stats.items()},
                   "draining": sorted(self._draining)}
        if self.roles is not None:
            out["roles"] = {k: list(v) for k, v in self.roles.items()}
        migs = self._finalize_migrations()
        out["migration_latency"] = self.migration_latency.snapshot()
        out["migration_phases"] = {
            p: h.snapshot()
            for p, h in sorted(self.migration_phases.items())}
        out["migrations_recorded"] = len(migs)
        if self.transport is not None:
            out["transport"] = {
                "ship_count": getattr(self.transport, "ship_count", 0),
                "ship_bytes": getattr(self.transport, "ship_bytes", 0),
                "fail_count": getattr(self.transport, "fail_count", 0)}
        out["replicas"] = {}
        for i, srv in enumerate(self.replicas):
            eng = srv.engine
            try:
                swap_resident = len(eng.swap_resident_rids())
            except Exception:
                swap_resident = 0
            out["replicas"][i] = {
                "alive": self.alive(i),
                "tp_degree": eng.tp_degree(),
                # host KV tier view: requests parked in this replica's
                # host RAM (resumable without recompute) and its spill
                # store's current size — the failover/capacity facts a
                # fleet controller reads per replica
                "kv_tier": {
                    "swap_resident": swap_resident,
                    "spill_blocks": len(getattr(eng, "_spill", ())),
                    # the spill store is BYTE-bounded (kv_host_spill_bytes
                    # engine arg): report occupancy in the bound's unit
                    "spill_bytes": getattr(eng, "_spill_bytes", 0),
                    "swap_out_bytes": eng.stats.get("kv_swap_out_bytes",
                                                    0),
                    "swap_in_bytes": eng.stats.get("kv_swap_in_bytes", 0),
                    "ship_out_bytes": eng.stats.get("kv_ship_out_bytes",
                                                    0),
                    "ship_in_bytes": eng.stats.get("kv_ship_in_bytes", 0),
                },
                "telemetry": srv.telemetry.snapshot()}
        return out

    def slo_report(self):
        """FLEET-level SLO/sensor report — the one view that answers
        "is tenant 3's p99 TTFT isolated while tenant 0 floods the
        queue, and on which replica?":

        * ``replicas`` — each replica's own :meth:`AsyncLLMServer
          .slo_report` (per-replica burn rates, alerts, pathologies);
        * ``fleet.slos`` — every SLO (union across replicas, by name)
          re-evaluated over the windowed latency samples CONCATENATED
          across the replica stores — a fleet burn rate, not an
          average of per-replica ones;
        * ``fleet.tenant_latency`` — per-tenant histograms merged
          BUCKET-WISE across replicas (exact at bucket resolution —
          per-replica p99s cannot be recombined);
        * ``fleet.alerts`` / ``fleet.pathologies`` — each replica's
          alert log and active detectors, replica-labeled;
        * ``router`` — the router-level store's snapshot (replica-
          labeled placement series) when one is attached.

        ``text`` is the human rendering."""
        from ..profiler.serving_telemetry import ServingTelemetry
        from ..profiler.slo import evaluate_slo, format_fleet_report
        replicas = {}
        merged = {}                  # tenant -> {family: LatencyHistogram}
        slos_by_name = {}
        stores = []
        alerts = []
        pathologies = {}
        for i, srv in enumerate(self.replicas):
            rep = srv.slo_report()
            replicas[i] = rep
            if srv.metrics_store is not None:
                stores.append(srv.metrics_store)
            if srv.slo_engine is not None:
                for s in srv.slo_engine.slos:
                    slos_by_name.setdefault(s.name, s)
            for t, fams in srv.telemetry.tenant_latency_hists().items():
                tgt = merged.setdefault(t, {})
                for n, h in fams.items():
                    if n in tgt:
                        tgt[n].merge(h)
                    else:
                        tgt[n] = h   # already a copy
            for a in rep["alerts"]:
                alerts.append({**a, "replica": i})
            for kind, active in rep["pathologies"].items():
                if active:
                    pathologies.setdefault(kind, []).append(i)
        now = time.monotonic()
        fleet_slos = []
        for s in slos_by_name.values():
            fast, slow = [], []
            truncated = False
            for store in stores:
                sl, fa, tr = store.windowed_values(
                    s.series_name, s.window_s,
                    fast_window_s=s.fast_window, now=now,
                    labels=s.series_labels)
                slow.extend(sl)
                fast.extend(fa)
                truncated = truncated or tr
            fleet_slos.append(evaluate_slo(s, fast, slow,
                                           window_truncated=truncated))
        out = {
            "replicas": replicas,
            "fleet": {
                "slos": fleet_slos,
                "tenant_latency":
                    ServingTelemetry.render_tenant_latency(merged),
                "alerts": alerts,
                "pathologies": pathologies,
            },
        }
        if self.metrics_store is not None:
            out["router"] = self.metrics_store.snapshot(max_samples=16)
        out["text"] = format_fleet_report(out)
        return out

    def prometheus_text(self):
        """One VALID Prometheus exposition across replicas: same-name
        series merge into one metric family (a single ``# TYPE`` line,
        then every replica's labeled samples) — naive concatenation
        would repeat TYPE lines per replica, which strict parsers
        reject. Each replica's telemetry must carry its own ``replica``
        label (``AsyncLLMServer(replica=i)``) or the merged samples
        would collide."""
        families = {}            # metric name -> (type_line, [samples])
        order = []
        for srv in self.replicas:
            current = None
            for line in srv.telemetry.prometheus_text().splitlines():
                if line.startswith("# TYPE "):
                    name = line.split()[2]
                    if name not in families:
                        families[name] = (line, [])
                        order.append(name)
                    current = name
                elif line:
                    families[current][1].append(line)
        out = []
        for name in order:
            type_line, samples = families[name]
            out.append(type_line)
            out.extend(samples)
        return "\n".join(out) + "\n"

    def export_merged_trace(self, path):
        """Merge every recorder-equipped replica's chrome trace into one
        Perfetto-loadable timeline — one process lane group per replica
        (rides :func:`paddle_tpu.profiler.merge_profile`, the same
        cross-rank merge training traces use) — then STITCH it:

        * every request whose spans landed on more than one (pid, tid)
          lane — a shipped decode leg, a failover resubmission — gets
          Perfetto FLOW events (``"ph":"s"`` → ``"ph":"f"``, matched on
          name+cat+id under
          :data:`~paddle_tpu.profiler.flight_recorder.FLOW_EVENT_NAME`)
          chaining its lanes in time order, so Perfetto renders the
          migrated request as ONE connected arrow-linked chain across
          replica pids;
        * each recorded migration renders its router-side phase spans
          (``kv_ship:serialize/transport/import/place``, timed where
          they ran) on a dedicated ``router:migrations`` process lane —
          the destination engine's ``kv_stitch`` span completes the
          decomposition on the decode replica's own lane.

        All replicas share this process's perf_counter clock, so
        cross-replica ordering is real — no alignment applied."""
        import tempfile

        from ..profiler import merge_profile
        from ..profiler.flight_recorder import FLOW_EVENT_NAME
        from .kv_transport import MIGRATION_PHASES

        with tempfile.TemporaryDirectory(
                prefix="paddle_tpu_cluster_trace_") as tmpd:
            files = []
            for i, srv in enumerate(self.replicas):
                rec = srv.flight_recorder
                if rec is None:
                    continue
                files.append(rec.export_chrome_trace(
                    os.path.join(tmpd, f"replica{i}.json")))
            if not files:
                raise RuntimeError(
                    "no replica has a flight recorder attached "
                    "(AsyncLLMServer(flight_recorder=True))")
            # same process, same perf_counter clock: keep it (align
            # would destroy cross-replica simultaneity)
            merge_profile(files, path, align_start=False)
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        # -- flow stitching: request lanes grouped by trace_id ----------
        lanes = {}       # trace_id -> {(pid, tid): (min_ts, max_end)}
        for ev in events:
            if ev.get("ph") != "X" or ev.get("cat") != "request":
                continue
            trace_id = (ev.get("args") or {}).get("trace_id")
            if trace_id is None:
                continue
            key = (ev["pid"], ev["tid"])
            lane = lanes.setdefault(trace_id, {})
            lo, hi = lane.get(key, (float("inf"), float("-inf")))
            lane[key] = (min(lo, ev["ts"]),
                         max(hi, ev["ts"] + ev.get("dur", 0.0)))
        flow_id = 0
        for trace_id in sorted(lanes):
            lane = lanes[trace_id]
            if len(lane) < 2:
                continue
            ordered = sorted(lane.items(), key=lambda kv: kv[1][0])
            for (ka, (_lo_a, hi_a)), (kb, (lo_b, _hi_b)) in zip(
                    ordered, ordered[1:]):
                flow_id += 1
                common = {"cat": "trace", "name": FLOW_EVENT_NAME,
                          "id": flow_id,
                          "args": {"trace_id": trace_id}}
                events.append({"ph": "s", "pid": ka[0], "tid": ka[1],
                               # the arrow leaves the earlier lane's
                               # last span and lands on the later
                               # lane's first — clamped so s <= f even
                               # when the lanes overlap in time
                               "ts": min(hi_a, lo_b), **common})
                events.append({"ph": "f", "bp": "e", "pid": kb[0],
                               "tid": kb[1], "ts": lo_b, **common})
        # -- the router's migration phase lane --------------------------
        migs = self._finalize_migrations()
        if migs:
            rpid = len(files)       # one past the last replica rank
            events.append({"ph": "M", "pid": rpid, "tid": 0,
                           "name": "process_name",
                           "args": {"name": "router:migrations"}})
            for m in migs:
                tid = 100 + int(m["rid"] or 0)
                events.append({"ph": "M", "pid": rpid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": f"migration rid "
                                                f"{m['rid']}"}})
                ts = m["t0"] * 1e6
                for p in MIGRATION_PHASES:
                    v = m["phases"].get(p)
                    if v is None or p == "stitch":
                        continue    # stitch renders on the decode lane
                    dur = max(v * 1e6, 1.0)
                    events.append({
                        "ph": "X", "cat": "migration", "pid": rpid,
                        "tid": tid, "name": f"kv_ship:{p}", "ts": ts,
                        "dur": dur,
                        "args": {"trace_id": m["trace_id"],
                                 "request_id": m["rid"],
                                 "src": m["src"], "dst": m["dst"],
                                 "bytes": m["bytes"]}})
                    ts += dur
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def explain_tail(self, quantile=0.99, top=None):
        """The FLEET-level slow-token explainer: join every replica
        recorder's request timelines by ``trace_id`` into one
        END-TO-END token stream per request — across KV ships,
        failovers, and restarts — and classify the worst inter-token
        gaps. A gap that stayed inside one replica gets that replica
        recorder's own :data:`~paddle_tpu.profiler.flight_recorder
        .TAIL_CAUSES` verdict (via ``classify_token_gap``); a gap
        spanning a replica boundary is attributed to the migration
        itself (``kv_ship:{phase}``, phase = the recorded migration's
        dominant phase — :data:`FLEET_TAIL_CAUSES`) when one covers it,
        else to the failover resubmission's re-prefill window
        (``failover_resubmit``). Entries carry ``trace_id``,
        ``request_id``/``replica`` of the LATER token, ``gap_s``,
        ``step_id``, ``cause``, and the migration's phase seconds when
        the cause is a ship phase."""
        migs = self._finalize_migrations()
        by_trace = {}
        for m in migs:
            if m["trace_id"] is not None:
                by_trace.setdefault(m["trace_id"], []).append(m)
        streams = {}
        for i, srv in enumerate(self.replicas):
            rec = srv.flight_recorder
            if rec is None:
                continue
            for rid, tl in rec.timelines().items():
                tc = tl.get("trace_ctx")
                key = tc["trace_id"] if tc else (i, rid)
                st = streams.setdefault(
                    key, {"trace_id": tc["trace_id"] if tc else None,
                          "tokens": [], "crashes": []})
                for ev in tl["events"]:
                    if ev["kind"] == "token":
                        st["tokens"].append(
                            (ev["t"], i, rid, ev["step_id"]))
                    elif ev["kind"] == "crashed":
                        st["crashes"].append(ev["t"])
        gaps = []
        for key, st in streams.items():
            toks = sorted(st["tokens"])
            for (t0, i0, _r0, _s0), (t1, i1, r1, s1) in zip(
                    toks, toks[1:]):
                gaps.append((t1 - t0, t0, t1, i0, i1, r1, s1, key, st))
        if not gaps:
            return []
        ordered = sorted(g[0] for g in gaps)
        thresh = ordered[min(int(quantile * len(ordered)),
                             len(ordered) - 1)]
        tail = sorted((g for g in gaps if g[0] >= thresh),
                      key=lambda g: -g[0])
        if top is not None:
            tail = tail[:top]
        out = []
        for gap, t0, t1, i0, i1, rid, sid, key, st in tail:
            entry = {"request_id": rid, "replica": i1,
                     "gap_s": round(gap, 6), "step_id": sid}
            if st["trace_id"] is not None:
                entry["trace_id"] = st["trace_id"]
            if i0 != i1:
                # the stream moved replicas inside this gap: either the
                # recorded migration explains it phase-by-phase, or it
                # was a failover's re-prefill window
                mig = next((m for m in by_trace.get(st["trace_id"], ())
                            if t0 <= m["t1"] and m["t0"] <= t1), None)
                if mig is not None and mig["phases"]:
                    phases = mig["phases"]
                    dom = max(phases, key=phases.get)
                    entry["cause"] = f"kv_ship:{dom}"
                    entry["migration"] = {
                        "src": mig["src"], "dst": mig["dst"],
                        "bytes": mig["bytes"],
                        "phases": {p: round(v, 6)
                                   for p, v in sorted(phases.items())}}
                else:
                    entry["cause"] = "failover_resubmit"
            elif any(t0 < ct <= t1 for ct in st["crashes"]):
                entry["cause"] = "restart_recovery"
            else:
                rec = self.replicas[i1].flight_recorder
                cause, _step = rec.classify_token_gap(rid, sid, gap)
                entry["cause"] = cause
            out.append(entry)
        return out

    def dump_debug_bundle(self, out_dir, reason="manual", detail=None):
        """Fleet postmortem under ``out_dir``: one black-box debug
        bundle PER replica (``replica{i}.json``), the merged stitched
        cross-replica trace (``merged_trace.json``, when any replica
        has a recorder), and the router's own view (``router.json``:
        snapshot + fleet explain_tail). Returns the path dict."""
        from ..profiler.black_box import collect_bundle, write_bundle
        os.makedirs(out_dir, exist_ok=True)
        paths = {"replicas": []}
        for i, srv in enumerate(self.replicas):
            p = os.path.join(out_dir, f"replica{i}.json")
            paths["replicas"].append(write_bundle(
                collect_bundle(server=srv, reason=reason,
                               detail=detail), p))
        if any(srv.flight_recorder is not None
               for srv in self.replicas):
            paths["trace"] = self.export_merged_trace(
                os.path.join(out_dir, "merged_trace.json"))
        rp = os.path.join(out_dir, "router.json")
        with open(rp, "w") as f:
            json.dump({"schema": "paddle_tpu.router_postmortem/v1",
                       "snapshot": self.snapshot(),
                       "explain_tail": self.explain_tail(0.0, top=16)},
                      f, sort_keys=True, indent=1, default=str)
        paths["router"] = rp
        return paths
