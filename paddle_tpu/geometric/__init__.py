"""paddle.geometric analog — graph message passing + sampling.

Reference: python/paddle/geometric/ (message_passing/send_recv.py send_u_recv /
send_ue_recv / send_uv, math.py segment_* ops, sampling/neighbors.py,
reindex.py). TPU-native: message passing lowers to gather + segment-reduce HLO
(sort-based scatter on TPU — the XLA analog of the reference's fused
graph_send_recv CUDA kernels); neighbor sampling is host-side numpy since graph
topology lives on host.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..ops.creation import to_tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "reindex_heter_graph",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]


def _ids_np(t):
    return np.asarray(t._value if isinstance(t, Tensor) else t)


def _np_rng():
    """numpy RNG derived from the framework Generator so paddle_tpu.seed()
    makes sampling reproducible (and rank-deterministic)."""
    from ..core import random as _random
    return np.random.default_rng(_random.default_generator.next_seed())


def _segment(reduce_op, data, segment_ids, num_segments):
    if reduce_op == "sum":
        return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                                  segment_ids, num_segments=num_segments)
        shape = (num_segments,) + (1,) * (data.ndim - 1)
        return s / jnp.maximum(cnt, 1).reshape(shape)
    if reduce_op == "max":
        return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    if reduce_op == "min":
        return jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    raise ValueError(f"unknown reduce_op {reduce_op!r}")


def _finalize_minmax(out, reduce_op):
    # XLA segment_max/min fill empty segments with ∓inf; reference uses 0
    if reduce_op in ("max", "min"):
        return jnp.where(jnp.isfinite(out), out, 0.0)
    return out


def _make_segment(reduce_op):
    def op(data, segment_ids, name=None):
        ids = jnp.asarray(_ids_np(segment_ids), dtype=jnp.int32)
        n = int(_ids_np(segment_ids).max()) + 1 if ids.shape[0] else 0

        def fn(d):
            return _finalize_minmax(_segment(reduce_op, d, ids, n), reduce_op)

        return dispatch(fn, (data,), {}, name=f"segment_{reduce_op}")

    op.__name__ = f"segment_{reduce_op}"
    return op


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_max = _make_segment("max")
segment_min = _make_segment("min")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges, reduce at destinations.
    Reference: message_passing/send_recv.py send_u_recv."""
    src = jnp.asarray(_ids_np(src_index), dtype=jnp.int32)
    dst = jnp.asarray(_ids_np(dst_index), dtype=jnp.int32)
    n_out = int(out_size) if out_size is not None else int(x.shape[0])

    def fn(v):
        return _finalize_minmax(_segment(reduce_op, v[src], dst, n_out),
                                reduce_op)

    return dispatch(fn, (x,), {}, name="send_u_recv")


_MESSAGE_OPS = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide,
}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine source-node features with edge features, reduce at dst.
    Reference: send_recv.py send_ue_recv (y = per-edge feature)."""
    if message_op not in _MESSAGE_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")
    src = jnp.asarray(_ids_np(src_index), dtype=jnp.int32)
    dst = jnp.asarray(_ids_np(dst_index), dtype=jnp.int32)
    n_out = int(out_size) if out_size is not None else int(x.shape[0])
    mfn = _MESSAGE_OPS[message_op]

    def fn(v, e):
        msg = mfn(v[src], e)
        return _finalize_minmax(_segment(reduce_op, msg, dst, n_out),
                                reduce_op)

    return dispatch(fn, (x, y), {}, name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (no reduction).
    Reference: send_recv.py send_uv."""
    if message_op not in _MESSAGE_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")
    src = jnp.asarray(_ids_np(src_index), dtype=jnp.int32)
    dst = jnp.asarray(_ids_np(dst_index), dtype=jnp.int32)
    mfn = _MESSAGE_OPS[message_op]

    def fn(xv, yv):
        return mfn(xv[src], yv[dst])

    return dispatch(fn, (x, y), {}, name="send_uv")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference: reindex.py
    reindex_graph). Returns (reindex_src, reindex_dst, out_nodes)."""
    xv = _ids_np(x).astype(np.int64)
    nb = _ids_np(neighbors).astype(np.int64)
    cnt = _ids_np(count).astype(np.int64)
    out_nodes = list(xv.tolist())
    mapping = {int(n): i for i, n in enumerate(xv.tolist())}
    for n in nb.tolist():
        if int(n) not in mapping:
            mapping[int(n)] = len(out_nodes)
            out_nodes.append(int(n))
    reindex_src = np.asarray([mapping[int(n)] for n in nb.tolist()],
                             dtype=np.int64)
    reindex_dst = np.repeat(np.arange(len(xv), dtype=np.int64), cnt)
    return (to_tensor(reindex_src), to_tensor(reindex_dst),
            to_tensor(np.asarray(out_nodes, dtype=np.int64)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling over a CSC graph (reference:
    sampling/neighbors.py sample_neighbors). Host-side numpy."""
    r = _ids_np(row).astype(np.int64)
    cp = _ids_np(colptr).astype(np.int64)
    nodes = _ids_np(input_nodes).astype(np.int64)
    rng = _np_rng()
    out_neighbors, out_count, out_eids = [], [], []
    for n in nodes.tolist():
        beg, end = int(cp[n]), int(cp[n + 1])
        neigh = r[beg:end]
        idx = np.arange(beg, end)
        if sample_size != -1 and len(neigh) > sample_size:
            pick = rng.choice(len(neigh), size=sample_size, replace=False)
            neigh = neigh[pick]
            idx = idx[pick]
        out_neighbors.append(neigh)
        out_count.append(len(neigh))
        out_eids.append(idx)
    neighbors = to_tensor(np.concatenate(out_neighbors)
                          if out_neighbors else np.zeros(0, np.int64))
    count = to_tensor(np.asarray(out_count, dtype=np.int64))
    if return_eids:
        if eids is None:
            raise ValueError("return_eids=True requires eids")
        e = _ids_np(eids)[np.concatenate(out_eids).astype(np.int64)] \
            if out_eids else np.zeros(0, np.int64)
        return neighbors, count, to_tensor(e)
    return neighbors, count


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted neighbor sampling (reference: sampling/neighbors.py
    weighted_sample_neighbors)."""
    r = _ids_np(row).astype(np.int64)
    cp = _ids_np(colptr).astype(np.int64)
    w = _ids_np(edge_weight).astype(np.float64)
    nodes = _ids_np(input_nodes).astype(np.int64)
    rng = _np_rng()
    out_neighbors, out_count, out_eids = [], [], []
    for n in nodes.tolist():
        beg, end = int(cp[n]), int(cp[n + 1])
        neigh = r[beg:end]
        idx = np.arange(beg, end)
        if sample_size != -1 and len(neigh) > sample_size:
            p = w[beg:end]
            p = p / p.sum()
            pick = rng.choice(len(neigh), size=sample_size, replace=False, p=p)
            neigh = neigh[pick]
            idx = idx[pick]
        out_neighbors.append(neigh)
        out_count.append(len(neigh))
        out_eids.append(idx)
    neighbors = to_tensor(np.concatenate(out_neighbors)
                          if out_neighbors else np.zeros(0, np.int64))
    count = to_tensor(np.asarray(out_count, dtype=np.int64))
    if return_eids:
        if eids is None:
            raise ValueError("return_eids=True requires eids")
        e = _ids_np(eids)[np.concatenate(out_eids).astype(np.int64)] \
            if out_eids else np.zeros(0, np.int64)
        return neighbors, count, to_tensor(e)
    return neighbors, count


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-graph reindex (reference: reindex.py
    reindex_heter_graph): one shared id mapping across all edge types;
    returns per-type (reindex_src list, reindex_dst list, out_nodes)."""
    xv = _ids_np(x).astype(np.int64)
    out_nodes = list(xv.tolist())
    mapping = {int(n): i for i, n in enumerate(xv.tolist())}
    srcs, dsts = [], []
    for nb_t, cnt_t in zip(neighbors, count):
        nb = _ids_np(nb_t).astype(np.int64)
        cnt = _ids_np(cnt_t).astype(np.int64)
        for n in nb.tolist():
            if int(n) not in mapping:
                mapping[int(n)] = len(out_nodes)
                out_nodes.append(int(n))
        srcs.append(to_tensor(np.asarray([mapping[int(n)] for n in nb.tolist()],
                                         dtype=np.int64)))
        dsts.append(to_tensor(np.repeat(np.arange(len(xv), dtype=np.int64),
                                        cnt)))
    reindex_src = to_tensor(np.concatenate(
        [np.asarray(s._value) for s in srcs])) if srcs else to_tensor(
        np.zeros(0, np.int64))
    reindex_dst = to_tensor(np.concatenate(
        [np.asarray(d._value) for d in dsts])) if dsts else to_tensor(
        np.zeros(0, np.int64))
    return (reindex_src, reindex_dst,
            to_tensor(np.asarray(out_nodes, dtype=np.int64)))
