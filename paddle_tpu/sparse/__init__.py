"""paddle.sparse analog — COO/CSR sparse tensors and ops.

Reference: python/paddle/sparse/ (creation.py sparse_coo_tensor/sparse_csr_tensor,
unary/binary/matmul ops lowering to phi/kernels/sparse/, 51 sparse op YAML entries —
SURVEY.md §2.2). TPU-native design: a sparse tensor is (static index arrays + a dense
``values`` Tensor). Compute lowers to gather / segment-sum HLO — XLA's sort/scatter on
TPU — instead of cuSPARSE; ``values`` rides the eager tape so every op here is
differentiable w.r.t. values, and the same functions trace under jit.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..ops.creation import to_tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor", "sparse_csr_tensor",
    "is_same_shape", "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "isnan", "mask_as", "slice", "pca_lowrank",
    "mv", "addmm", "transpose", "reshape", "sum", "coalesce",
    "relu", "relu6", "leaky_relu", "sigmoid", "tanh", "softmax", "sqrt", "square",
    "sin", "sinh", "tan", "asin", "asinh", "atan", "atanh", "abs", "pow",
    "cast", "neg", "expm1", "log1p", "rad2deg", "deg2rad", "is_sparse_coo",
    "is_sparse_csr", "nn",
]


def _as_value(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor: ``indices`` (sparse_dim, nnz) int64 + ``values``.

    Values may carry trailing dense dims (hybrid tensors), matching the reference's
    SparseCooTensor (paddle/phi/core/sparse_coo_tensor.h).
    """

    def __init__(self, indices, values: Tensor, shape, coalesced=False):
        self._indices = jnp.asarray(_as_value(indices), dtype=jnp.int64)
        self._values = values if isinstance(values, Tensor) else to_tensor(values)
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = bool(coalesced)

    # -- metadata -----------------------------------------------------------
    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    def nnz(self):
        return int(self._indices.shape[1])

    def sparse_dim(self):
        return int(self._indices.shape[0])

    def dense_dim(self):
        return len(self._shape) - self.sparse_dim()

    def indices(self) -> Tensor:
        return to_tensor(self._indices)

    def values(self) -> Tensor:
        return self._values

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def is_sparse(self):
        return True

    # -- conversions --------------------------------------------------------
    def to_dense(self) -> Tensor:
        idx = self._indices
        shape = self._shape
        sd = self.sparse_dim()

        def fn(v):
            out = jnp.zeros(shape, dtype=v.dtype)
            return out.at[tuple(idx[d] for d in range(sd))].add(v)

        return dispatch(fn, (self._values,), {}, name="sparse_to_dense")

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if self.sparse_dim() != 2:
            raise ValueError("to_sparse_csr requires a 2-sparse-dim COO tensor")
        st = self.coalesce()
        rows = np.asarray(st._indices[0])
        cols = jnp.asarray(st._indices[1])
        nrows = st._shape[0]
        crows = jnp.asarray(
            np.concatenate([[0], np.cumsum(np.bincount(rows, minlength=nrows))]),
            dtype=jnp.int64)
        return SparseCsrTensor(crows, cols, st._values, st._shape)

    def coalesce(self) -> "SparseCooTensor":
        if self._coalesced:
            return self
        idx = np.asarray(self._indices)
        sd = idx.shape[0]
        flat = np.ravel_multi_index(tuple(idx), self._shape[:sd])
        order = np.argsort(flat, kind="stable")
        sorted_flat = flat[order]
        uniq, first = np.unique(sorted_flat, return_index=True)
        seg_ids = jnp.asarray(np.searchsorted(uniq, sorted_flat))
        n_uniq = len(uniq)
        order_j = jnp.asarray(order)

        def fn(v):
            return jax.ops.segment_sum(v[order_j], seg_ids, num_segments=n_uniq)

        new_vals = dispatch(fn, (self._values,), {}, name="sparse_coalesce")
        new_idx = np.stack(np.unravel_index(uniq, self._shape[:sd]))
        return SparseCooTensor(new_idx, new_vals, self._shape, coalesced=True)

    # -- operators ----------------------------------------------------------
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __truediv__(self, other):
        return divide(self, other)

    def __neg__(self):
        return neg(self)

    def __matmul__(self, other):
        return matmul(self, other)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def numpy(self):
        return self.to_dense().numpy()

    def backward(self, grad=None):
        raise RuntimeError("call .backward() on a dense result, not the sparse leaf")

    def T(self):
        return transpose(self, list(range(self.ndim))[::-1])

    def astype(self, dtype):
        return cast(self, dtype)


class SparseCsrTensor:
    """CSR sparse matrix (optionally batched): crows, cols, values.

    Reference: paddle/phi/core/sparse_csr_tensor.h.
    """

    def __init__(self, crows, cols, values: Tensor, shape):
        self._crows = jnp.asarray(_as_value(crows), dtype=jnp.int64)
        self._cols = jnp.asarray(_as_value(cols), dtype=jnp.int64)
        self._values = values if isinstance(values, Tensor) else to_tensor(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    def nnz(self):
        return int(self._cols.shape[-1])

    def crows(self) -> Tensor:
        return to_tensor(self._crows)

    def cols(self) -> Tensor:
        return to_tensor(self._cols)

    def values(self) -> Tensor:
        return self._values

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def is_sparse(self):
        return True

    def _row_ids(self):
        crows = np.asarray(self._crows)
        if crows.ndim == 1:
            counts = np.diff(crows)
            return np.repeat(np.arange(len(counts)), counts)
        # batched CSR: crows (B, R+1), uniform nnz per batch (reference layout)
        counts = np.diff(crows, axis=-1)  # (B, R)
        per_batch = counts.sum(axis=1)
        if not (per_batch == per_batch[0]).all():
            raise ValueError("batched CSR requires equal nnz per batch")
        nrows = counts.shape[1]
        return np.stack([np.repeat(np.arange(nrows), c) for c in counts])

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        rows_np = self._row_ids()
        cols = np.asarray(self._cols)
        if rows_np.ndim == 1:
            idx = np.stack([rows_np, cols])
        else:
            # (B, nnz_b) rows/cols -> 3-sparse-dim COO with a batch row
            nb, nnz_b = rows_np.shape
            batch = np.repeat(np.arange(nb), nnz_b)
            idx = np.stack([batch, rows_np.reshape(-1), cols.reshape(-1)])
        vals = self._values
        if len(vals.shape) > 1 and rows_np.ndim > 1:
            vals = dispatch(lambda v: v.reshape((-1,) + v.shape[2:]), (vals,), {},
                            name="csr_batch_flatten")
        return SparseCooTensor(idx, vals, self._shape, coalesced=True)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def numpy(self):
        return self.to_dense().numpy()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor (reference: python/paddle/sparse/creation.py)."""
    idx = np.asarray(_as_value(indices), dtype=np.int64)
    vals = values if isinstance(values, Tensor) else to_tensor(values, dtype=dtype)
    if dtype is not None and isinstance(values, Tensor):
        from ..core.dtype import convert_dtype
        jd = convert_dtype(dtype)
        vals = dispatch(lambda v: v.astype(jd), (vals,), {},
                        name="sparse_values_cast")
    if shape is None:
        sparse_shape = tuple((idx.max(axis=1) + 1).tolist()) if idx.size else ()
        shape = sparse_shape + tuple(vals.shape[1:])
    if not isinstance(values, Tensor):
        vals.stop_gradient = stop_gradient
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = values if isinstance(values, Tensor) else to_tensor(values, dtype=dtype)
    if not isinstance(values, Tensor):
        vals.stop_gradient = stop_gradient
    return SparseCsrTensor(crows, cols, vals, shape)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _coo(x) -> SparseCooTensor:
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


# ---------------------------------------------------------------------------
# unary ops (apply to values, sparsity preserved)
# ---------------------------------------------------------------------------

def _unary(jfn, op_name):
    def op(x, name=None):
        csr = isinstance(x, SparseCsrTensor)
        xc = _coo(x)

        def fn(v):
            return jfn(v)

        out_vals = dispatch(fn, (xc._values,), {}, name=f"sparse_{op_name}")
        out = SparseCooTensor(xc._indices, out_vals, xc._shape, xc._coalesced)
        return out.to_sparse_csr() if csr else out

    op.__name__ = op_name
    return op


relu = _unary(lambda v: jnp.maximum(v, 0), "relu")
relu6 = _unary(lambda v: jnp.clip(v, 0, 6), "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
sqrt = _unary(jnp.sqrt, "sqrt")
square = _unary(jnp.square, "square")
sin = _unary(jnp.sin, "sin")
sinh = _unary(jnp.sinh, "sinh")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
asinh = _unary(jnp.arcsinh, "asinh")
atan = _unary(jnp.arctan, "atan")
atanh = _unary(jnp.arctanh, "atanh")
abs = _unary(jnp.abs, "abs")
neg = _unary(jnp.negative, "neg")
expm1 = _unary(jnp.expm1, "expm1")
log1p = _unary(jnp.log1p, "log1p")
rad2deg = _unary(jnp.rad2deg, "rad2deg")
deg2rad = _unary(jnp.deg2rad, "deg2rad")


def leaky_relu(x, negative_slope=0.01):
    return _unary(lambda v: jnp.where(v >= 0, v, negative_slope * v), "leaky_relu")(x)


def pow(x, factor):
    return _unary(lambda v: jnp.power(v, factor), "pow")(x)


def cast(x, index_dtype=None, value_dtype=None):
    """paddle.sparse.cast(x, index_dtype, value_dtype) — argument order matches
    the reference (python/paddle/sparse/unary.py)."""
    from ..core import dtype as dtypes
    jd = dtypes.convert_dtype(value_dtype) if value_dtype is not None else None
    ji = dtypes.convert_dtype(index_dtype) if index_dtype is not None else None

    def conv(s):
        vals = s._values if jd is None else dispatch(
            lambda v: v.astype(jd), (s._values,), {}, name="sparse_cast")
        return vals

    if isinstance(x, SparseCsrTensor):
        out = SparseCsrTensor(x._crows, x._cols, conv(x), x._shape)
        if ji is not None:
            out._crows = out._crows.astype(ji)
            out._cols = out._cols.astype(ji)
        return out
    out = SparseCooTensor(x._indices, conv(x), x._shape, x._coalesced)
    if ji is not None:
        out._indices = out._indices.astype(ji)
    return out


# ---------------------------------------------------------------------------
# binary elementwise (union of sparsity patterns)
# ---------------------------------------------------------------------------

def _binary(jfn, op_name):
    def op(x, y, name=None):
        csr = isinstance(x, SparseCsrTensor)
        if isinstance(y, Tensor) or np.isscalar(y):
            raise TypeError(
                f"sparse.{op_name} requires two sparse tensors; "
                "use dense ops for mixed")
        xc, yc = _coo(x).coalesce(), _coo(y).coalesce()
        if xc._shape != yc._shape:
            raise ValueError(f"shape mismatch: {xc._shape} vs {yc._shape}")
        sd = xc.sparse_dim()
        xi = np.asarray(xc._indices)
        yi = np.asarray(yc._indices)
        xf = np.ravel_multi_index(tuple(xi), xc._shape[:sd])
        yf = np.ravel_multi_index(tuple(yi), yc._shape[:sd])
        union = np.union1d(xf, yf)
        xpos = jnp.asarray(np.searchsorted(union, xf))
        ypos = jnp.asarray(np.searchsorted(union, yf))
        n = len(union)
        dense_shape = tuple(xc._values.shape[1:])

        def fn(vx, vy):
            ax = jnp.zeros((n,) + dense_shape, dtype=vx.dtype).at[xpos].set(vx)
            ay = jnp.zeros((n,) + dense_shape, dtype=vy.dtype).at[ypos].set(vy)
            return jfn(ax, ay)

        out_vals = dispatch(fn, (xc._values, yc._values), {},
                            name=f"sparse_{op_name}")
        new_idx = np.stack(np.unravel_index(union, xc._shape[:sd]))
        out = SparseCooTensor(new_idx, out_vals, xc._shape, coalesced=True)
        return out.to_sparse_csr() if csr else out

    op.__name__ = op_name
    return op


add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------

def matmul(x, y, name=None):
    """Sparse @ dense (spmm) or sparse @ sparse → dense.

    Reference: python/paddle/sparse/binary.py matmul → phi sparse matmul kernels
    (cuSPARSE on GPU). Here: gather rows of the dense operand by the sparse column
    ids, scale by values, segment-sum into output rows — sort/scatter HLO on TPU.
    """
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xc = _coo(x).coalesce()
        if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
            y = y.to_dense()
        if xc.ndim != 2:
            raise ValueError("sparse matmul currently supports 2-D sparse operands")
        rows = jnp.asarray(xc._indices[0])
        cols = jnp.asarray(xc._indices[1])
        n_rows = xc._shape[0]

        def fn(v, d):
            gathered = d[cols] * v[(...,) + (None,) * (d.ndim - 1)]
            return jax.ops.segment_sum(gathered, rows, num_segments=n_rows)

        return dispatch(fn, (xc._values, y), {}, name="sparse_matmul")
    raise TypeError("matmul: first operand must be sparse")


def mv(x, vec, name=None):
    xc = _coo(x).coalesce()
    rows = jnp.asarray(xc._indices[0])
    cols = jnp.asarray(xc._indices[1])
    n_rows = xc._shape[0]

    def fn(v, d):
        return jax.ops.segment_sum(v * d[cols], rows, num_segments=n_rows)

    return dispatch(fn, (xc._values, vec), {}, name="sparse_mv")


def masked_matmul(x, y, mask, name=None):
    """SDDMM: (x @ y) sampled at mask's sparsity (reference: sparse/binary.py)."""
    mc = _coo(mask)
    rows = jnp.asarray(mc._indices[0])
    cols = jnp.asarray(mc._indices[1])

    def fn(a, b):
        return jnp.einsum("nk,nk->n", a[rows, :], jnp.swapaxes(b, -1, -2)[cols, :])

    vals = dispatch(fn, (x, y), {}, name="sparse_masked_matmul")
    out = SparseCooTensor(mc._indices, vals, (x.shape[0], y.shape[-1]),
                          coalesced=mc._coalesced)
    return out.to_sparse_csr() if isinstance(mask, SparseCsrTensor) else out


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) with sparse x (reference: sparse/binary.py)."""
    prod = matmul(x, y)

    def fn(inp, p):
        return beta * inp + alpha * p

    return dispatch(fn, (input, prod), {}, name="sparse_addmm")


# ---------------------------------------------------------------------------
# structure ops
# ---------------------------------------------------------------------------

def transpose(x, perm, name=None):
    csr = isinstance(x, SparseCsrTensor)
    xc = _coo(x)
    sd = xc.sparse_dim()
    if sorted(perm) != list(range(xc.ndim)):
        raise ValueError(f"invalid perm {perm}")
    if sorted(perm[:sd]) != list(range(sd)):
        raise ValueError("transpose across sparse/dense boundary is not supported")
    new_idx = xc._indices[jnp.asarray(perm[:sd])]
    dense_perm = [0] + [p - sd + 1 for p in perm[sd:]]
    vals = xc._values
    if dense_perm != list(range(len(dense_perm))):
        vals = dispatch(lambda v: jnp.transpose(v, dense_perm), (vals,), {},
                        name="sparse_transpose_vals")
    new_shape = tuple(xc._shape[p] for p in perm)
    out = SparseCooTensor(new_idx, vals, new_shape, coalesced=False)
    return out.to_sparse_csr() if csr else out


def reshape(x, shape, name=None):
    csr = isinstance(x, SparseCsrTensor)
    xc = _coo(x).coalesce()
    sd = xc.sparse_dim()
    if xc.dense_dim():
        raise ValueError("reshape of hybrid sparse tensors is not supported")
    shape = list(shape)
    numel = int(np.prod(xc._shape))
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = numel // known
    flat = np.ravel_multi_index(tuple(np.asarray(xc._indices)), xc._shape)
    new_idx = np.stack(np.unravel_index(flat, shape))
    out = SparseCooTensor(new_idx, xc._values, shape, coalesced=True)
    return out.to_sparse_csr() if csr else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    xc = _coo(x).coalesce()
    if axis is None:
        def fn(v):
            out = jnp.sum(v)
            return out if dtype is None else out.astype(dtype)

        return dispatch(fn, (xc._values,), {}, name="sparse_sum")
    if isinstance(axis, (list, tuple)):
        raise ValueError("sparse.sum supports a single axis or None")
    axis = axis % xc.ndim
    sd = xc.sparse_dim()
    if axis >= sd:
        vals = dispatch(lambda v: jnp.sum(v, axis=axis - sd + 1, keepdims=keepdim),
                        (xc._values,), {}, name="sparse_sum")
        new_shape = [s for i, s in enumerate(xc._shape) if i != axis or keepdim]
        if keepdim:
            new_shape = list(xc._shape)
            new_shape[axis] = 1
        return SparseCooTensor(xc._indices, vals, new_shape, coalesced=True)
    keep_dims = [d for d in range(sd) if d != axis]
    new_sparse_shape = tuple(xc._shape[d] for d in keep_dims)
    if keepdim:
        full_shape = list(xc._shape)
        full_shape[axis] = 1
    else:
        full_shape = [s for i, s in enumerate(xc._shape) if i != axis]
    idx = np.asarray(xc._indices)
    if keep_dims:
        flat = np.ravel_multi_index(tuple(idx[keep_dims]), new_sparse_shape)
    else:
        flat = np.zeros(idx.shape[1], dtype=np.int64)
    uniq = np.unique(flat)
    seg = jnp.asarray(np.searchsorted(uniq, flat))
    n = len(uniq)

    def fn(v):
        return jax.ops.segment_sum(v, seg, num_segments=n)

    vals = dispatch(fn, (xc._values,), {}, name="sparse_sum")
    if keep_dims:
        new_idx = np.stack(np.unravel_index(uniq, new_sparse_shape))
    else:
        new_idx = np.zeros((0, len(uniq)), dtype=np.int64)
    if keepdim:
        ins_row = np.zeros((1, new_idx.shape[1]), dtype=np.int64)
        new_idx = np.concatenate(
            [new_idx[:axis], ins_row, new_idx[axis:]], axis=0)
    return SparseCooTensor(new_idx, vals, full_shape, coalesced=True)


def coalesce(x, name=None):
    return x.coalesce()


def softmax(x, axis=-1, name=None):
    """Row softmax over the sparsity pattern (reference: sparse/nn/functional).

    Rows are identified by ALL sparse dims except the last, so batched (B, M, N)
    COO inputs normalize per true row, matching the reference.
    """
    csr = isinstance(x, SparseCsrTensor)
    xc = _coo(x).coalesce()
    sd = xc.sparse_dim()
    if axis not in (-1, sd - 1):
        raise ValueError("sparse softmax supports the last (column) axis")
    idx = np.asarray(xc._indices)
    if sd == 1:
        row_ids = np.zeros(idx.shape[1], dtype=np.int64)
        n_rows = 1
    else:
        row_shape = xc._shape[:sd - 1]
        row_ids = np.ravel_multi_index(tuple(idx[:sd - 1]), row_shape)
        n_rows = int(np.prod(row_shape))
    rows = jnp.asarray(row_ids)

    def fn(v):
        row_max = jax.ops.segment_max(v, rows, num_segments=n_rows)
        e = jnp.exp(v - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=n_rows)
        return e / denom[rows]

    vals = dispatch(fn, (xc._values,), {}, name="sparse_softmax")
    out = SparseCooTensor(xc._indices, vals, xc._shape, coalesced=True)
    return out.to_sparse_csr() if csr else out


from . import nn  # noqa: E402,F401


def isnan(x, name=None):
    """reference: sparse/unary.py isnan — elementwise on stored values."""
    c = _coo(x)
    vals = dispatch(lambda v: jnp.isnan(v), (c.values(),), {},
                    name="sparse_isnan")
    out = SparseCooTensor(c.indices(), vals, c.shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def mask_as(x, mask, name=None):
    """Sample a DENSE tensor at a sparse mask's pattern (reference:
    sparse/multiary.py mask_as)."""
    mc = _coo(mask)

    def fn(dense, idx):
        return dense[tuple(idx[i] for i in range(idx.shape[0]))]
    vals = dispatch(fn, (x, mc.indices()), {}, name="sparse_mask_as")
    out = SparseCooTensor(mc.indices(), vals, mc.shape)
    return out.to_sparse_csr() if isinstance(mask, SparseCsrTensor) else out


def slice(x, axes, starts, ends, name=None):
    """reference: sparse/unary.py slice — dense-semantics slice of a sparse
    tensor (static-index design: filter stored entries + shift indices)."""
    import numpy as _np
    c = _coo(x)
    idx = _np.asarray(c.indices()._value)
    vals = c.values()
    shape = list(c.shape)
    axes = [a % len(shape) for a in axes]
    keep = _np.ones(idx.shape[1], bool)
    for a, st, en in zip(axes, starts, ends):
        st = st + shape[a] if st < 0 else st
        en = en + shape[a] if en < 0 else min(en, shape[a])
        keep &= (idx[a] >= st) & (idx[a] < en)
        shape[a] = max(0, min(en, shape[a]) - st)
    sel = _np.nonzero(keep)[0]
    new_idx = idx[:, sel].copy()
    for a, st, en in zip(axes, starts, ends):
        st = st + c.shape[a] if st < 0 else st
        new_idx[a] -= st
    new_vals = dispatch(lambda v: v[jnp.asarray(sel)], (vals,), {},
                        name="sparse_slice_values")
    out = SparseCooTensor(to_tensor(new_idx), new_vals, shape)
    return out.to_sparse_csr() if isinstance(x, SparseCsrTensor) else out


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference: sparse/multiary.py pca_lowrank — densify then randomized
    PCA (the GPU reference also materializes for the power iteration)."""
    from ..ops.linalg import pca_lowrank as _dense_pca
    dense = x.to_dense() if hasattr(x, "to_dense") else x
    return _dense_pca(dense, q=q, center=center, niter=niter)
