"""paddle.sparse.nn analog — layers over sparse tensors.

Reference: python/paddle/sparse/nn/ (Conv3D/SubmConv3D riding phi sparse conv kernels,
BatchNorm on nnz values, activations). TPU-native: activations/norms act on the dense
``values`` tensor; 3-D convolutions compute densely through XLA's conv HLO and
re-sparsify at the (statically known) active output sites — on TPU the conv is the
MXU-friendly part, and active-site bookkeeping is host-side index arithmetic since
sparsity patterns are static per tensor in this design.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..nn.layer_base import Layer
from ..nn import initializer as I
from . import (
    SparseCooTensor, SparseCsrTensor, relu as _relu, relu6 as _relu6,
    leaky_relu as _leaky_relu, softmax as _softmax, _coo,
)

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm", "SyncBatchNorm",
    "Conv2D", "SubmConv2D", "Conv3D", "SubmConv3D", "MaxPool3D", "functional",
]


class ReLU(Layer):
    def forward(self, x):
        return _relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return _relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return _leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return _softmax(x, self._axis)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) dim of a sparse tensor's values.

    Reference: python/paddle/sparse/nn/layer/norm.py — stats are computed over nnz
    entries only, exactly as the reference's sparse BN does.
    """

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NDHWC", name=None):
        super().__init__()
        self._momentum = momentum
        self._eps = epsilon
        self.weight = self.create_parameter([num_features], attr=weight_attr,
                                            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer(
            "_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        xc = _coo(x)
        vals = xc._values
        training = self.training
        mom = self._momentum
        eps = self._eps

        if training:
            def fn(v, w, b, rm, rv):
                axes = tuple(range(v.ndim - 1))
                mean = jnp.mean(v, axis=axes)
                var = jnp.var(v, axis=axes)
                out = (v - mean) / jnp.sqrt(var + eps) * w + b
                return out, mom * rm + (1 - mom) * mean, mom * rv + (1 - mom) * var

            out, new_m, new_v = dispatch(
                fn, (vals, self.weight, self.bias, self._mean, self._variance), {},
                name="sparse_batch_norm")
            self._mean._value = new_m._value
            self._variance._value = new_v._value
        else:
            def fn(v, w, b, rm, rv):
                return (v - rm) / jnp.sqrt(rv + eps) * w + b

            out = dispatch(fn, (vals, self.weight, self.bias, self._mean,
                                self._variance), {}, name="sparse_batch_norm_infer")
        res = SparseCooTensor(xc._indices, out, xc._shape, xc._coalesced)
        return res.to_sparse_csr() if isinstance(x, SparseCsrTensor) else res


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse BN; under pjit the mean/var reductions are global when the
    batch dim is sharded (XLA inserts the psum), so the single-program form suffices.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


def _footprint_out_sites(idx, N, spatial_in, ks, stride, pad, dilation):
    """All output sites whose window covers ≥1 active input site (any ndims).

    Shared by the sparse convs and pools: an output site o covers input c
    when o*stride + off*dilation - pad == c for some off in [0, k);
    enumerate all (site, off) pairs and keep in-range strided solutions.
    """
    nd = len(ks)
    out_spatial = []
    for i in range(nd):
        eff_k = (ks[i] - 1) * dilation[i] + 1
        out_spatial.append(
            (spatial_in[i] + 2 * int(pad[i]) - eff_k) // stride[i] + 1)
    offs = np.stack(np.meshgrid(
        *[np.arange(k) * d for k, d in zip(ks, dilation)],
        indexing="ij"), axis=-1).reshape(-1, nd)
    coords = idx[1:1 + nd].T  # (nnz, nd)
    pad_arr = np.asarray([int(p) for p in pad])
    expanded = (coords[:, None, :] + pad_arr - offs[None, :, :])
    batch = np.repeat(idx[0], offs.shape[0])
    expanded = expanded.reshape(-1, nd)
    stride_arr = np.asarray(stride)
    valid = np.all(expanded % stride_arr == 0, axis=1)
    outc = expanded // stride_arr
    for i in range(nd):
        valid &= (outc[:, i] >= 0) & (outc[:, i] < out_spatial[i])
    outc = outc[valid]
    batch = batch[valid]
    full = np.concatenate([batch[:, None], outc], axis=1)
    flat = np.ravel_multi_index(full.T, (N,) + tuple(out_spatial))
    uniq = np.unique(flat)
    out_idx = np.stack(np.unravel_index(uniq, (N,) + tuple(out_spatial)))
    return out_idx, tuple(out_spatial)


_CONV_DIMS = {2: ("NHWC", "HWIO", "NHWC"), 3: ("NDHWC", "DHWIO", "NDHWC")}


def _dense_conv(v_dense, w, stride, padding, dilation, groups, nd):
    # v_dense: (N, *spatial, C) channels-last; w: (*k, Cin/g, Cout)
    dn = jax.lax.conv_dimension_numbers(v_dense.shape, w.shape, _CONV_DIMS[nd])
    if isinstance(padding, str):
        pad = padding
    else:
        p = padding if isinstance(padding, (list, tuple)) else [padding] * nd
        pad = [(int(x), int(x)) for x in p]
    return jax.lax.conv_general_dilated(
        v_dense, w, window_strides=tuple(stride), padding=pad,
        rhs_dilation=tuple(dilation), dimension_numbers=dn,
        feature_group_count=groups)


def _sparse_conv_forward(x, weight, bias, ks, stride, padding, dilation,
                         groups, subm, nd):
    """Shared core of Conv2D/Conv3D (layer + functional forms)."""
    xc = _coo(x)
    idx_np = np.asarray(xc._indices)
    shape = tuple(xc._shape)
    N, spatial_in = shape[0], shape[1:1 + nd]
    if subm:
        out_idx, out_spatial = idx_np, tuple(spatial_in)
    else:
        pad = padding if isinstance(padding, (list, tuple)) else [padding] * nd
        out_idx, out_spatial = _footprint_out_sites(
            idx_np, N, spatial_in, ks, stride, pad, dilation)
    idx = jnp.asarray(xc._indices)
    oidx = jnp.asarray(out_idx)
    w_shape = weight.shape
    out_ch = int(w_shape[-1])

    def fn(v, w, b):
        dense = jnp.zeros(shape[:1 + nd] + (v.shape[-1],), dtype=v.dtype)
        dense = dense.at[tuple(idx[i] for i in range(1 + nd))].add(v)
        out = _dense_conv(dense, w, stride, padding, dilation, groups, nd)
        vals = out[tuple(oidx[i] for i in range(1 + nd))]
        if b is not None:
            vals = vals + b
        return vals

    vals = dispatch(fn, (xc._values, weight, bias), {},
                    name=f"sparse_conv{nd}d")
    out_shape = (shape[0],) + out_spatial + (out_ch,)
    return SparseCooTensor(out_idx, vals, out_shape, coalesced=True)


def _max_pool_forward(x, ks, stride, padding, nd):
    xc = _coo(x)
    shape = tuple(xc._shape)
    N, spatial_in, C = shape[0], shape[1:1 + nd], shape[1 + nd]
    pad = [int(p) for p in (padding if isinstance(padding, (list, tuple))
                            else [padding] * nd)]
    idx_np = np.asarray(xc._indices)
    out_idx, out_spatial = _footprint_out_sites(
        idx_np, N, spatial_in, ks, stride, pad, (1,) * nd)
    idx = jnp.asarray(xc._indices)
    oidx = jnp.asarray(out_idx)

    def fn(v):
        neg = jnp.asarray(-jnp.inf, dtype=v.dtype)
        dense = jnp.full(shape, neg)
        dense = dense.at[tuple(idx[i] for i in range(1 + nd))].max(v)
        pooled = jax.lax.reduce_window(
            dense, neg, jax.lax.max,
            window_dimensions=(1,) + tuple(ks) + (1,),
            window_strides=(1,) + tuple(stride) + (1,),
            padding=((0, 0),) + tuple((p, p) for p in pad) + ((0, 0),))
        return pooled[tuple(oidx[i] for i in range(1 + nd))]

    vals = dispatch(fn, (xc._values,), {}, name=f"sparse_max_pool{nd}d")
    return SparseCooTensor(out_idx, vals, (N,) + out_spatial + (C,),
                           coalesced=True)


class _SparseConvNd(Layer):
    """Sparse convolution (channels-last), reference sparse/nn/layer/conv.py.

    Computes through the dense conv HLO and gathers the statically-derived
    active output sites (host-side index arithmetic; sparsity patterns are
    static per tensor in this design).
    """

    _subm = False
    _nd = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format=None):
        super().__init__()
        nd = self._nd
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * nd
        self._ks = tuple(int(k) for k in ks)
        st = stride if isinstance(stride, (list, tuple)) else [stride] * nd
        self._stride = tuple(int(s) for s in st)
        if isinstance(padding, str):
            mode = padding.upper()
            if mode == "VALID":
                padding = 0
            elif mode == "SAME":
                if any(s != 1 for s in self._stride):
                    raise ValueError("padding='SAME' requires stride 1")
                padding = tuple((k - 1) // 2 for k in self._ks)
            else:
                raise ValueError(f"unknown padding mode {padding!r}")
        self._padding = padding
        dl = dilation if isinstance(dilation, (list, tuple)) else [dilation] * nd
        self._dilation = tuple(int(d) for d in dl)
        self._groups = groups
        self.weight = self.create_parameter(
            list(self._ks) + [in_channels // groups, out_channels], attr=weight_attr)
        self.bias = (self.create_parameter([out_channels], attr=bias_attr, is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return _sparse_conv_forward(
            x, self.weight, self.bias, self._ks, self._stride, self._padding,
            self._dilation, self._groups, self._subm, self._nd)


class Conv3D(_SparseConvNd):
    _nd = 3


class Conv2D(_SparseConvNd):
    """Sparse 2-D convolution (NHWC), reference sparse/nn/layer/conv.py
    Conv2D."""
    _nd = 2


class _SubmMixin:
    """Submanifold sparse conv: output sparsity == input sparsity."""

    _subm = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self._stride != (1,) * self._nd:
            raise ValueError("submanifold conv requires stride 1")
        # 'same' padding so sites map onto themselves
        self._padding = tuple(((k - 1) * d) // 2
                              for k, d in zip(self._ks, self._dilation))


class SubmConv3D(_SubmMixin, Conv3D):
    pass


class SubmConv2D(_SubmMixin, Conv2D):
    pass


class MaxPool3D(Layer):
    """Sparse max pool (NDHWC), dense window-reduce + active-site gather."""

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * 3
        self._ks = tuple(int(k) for k in ks)
        st = stride if stride is not None else kernel_size
        st = st if isinstance(st, (list, tuple)) else [st] * 3
        self._stride = tuple(int(s) for s in st)
        self._padding = padding if isinstance(padding, (list, tuple)) else [padding] * 3

    def forward(self, x):
        return _max_pool_forward(x, self._ks, self._stride, self._padding, 3)


def _norm_tuple(v, nd):
    return tuple(int(x) for x in (v if isinstance(v, (list, tuple))
                                  else [v] * nd))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    """Functional sparse 2-D conv (reference: sparse/nn/functional/conv.py).
    weight: (kh, kw, Cin/g, Cout)."""
    ks = tuple(int(k) for k in weight.shape[:2])
    return _sparse_conv_forward(x, weight, bias, ks, _norm_tuple(stride, 2),
                                padding, _norm_tuple(dilation, 2), groups,
                                False, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Functional sparse 3-D conv. weight: (kd, kh, kw, Cin/g, Cout)."""
    ks = tuple(int(k) for k in weight.shape[:3])
    return _sparse_conv_forward(x, weight, bias, ks, _norm_tuple(stride, 3),
                                padding, _norm_tuple(dilation, 3), groups,
                                False, 3)


def _subm_conv(x, weight, bias, stride, padding, dilation, groups, nd, key):
    nd_ks = tuple(int(k) for k in weight.shape[:nd])
    stride = _norm_tuple(stride, nd)
    if stride != (1,) * nd:
        raise ValueError("submanifold conv requires stride 1")
    dilation = _norm_tuple(dilation, nd)
    pad = tuple(((k - 1) * d) // 2 for k, d in zip(nd_ks, dilation))
    return _sparse_conv_forward(x, weight, bias, nd_ks, stride, pad,
                                dilation, groups, True, nd)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _subm_conv(x, weight, bias, stride, padding, dilation, groups, 2,
                      key)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _subm_conv(x, weight, bias, stride, padding, dilation, groups, 3,
                      key)


# the reference's implicit-GEMM kernels are an execution strategy, not a
# semantic: on TPU both forms lower through the same dense conv HLO
subm_conv2d_igemm = subm_conv2d
subm_conv3d_igemm = subm_conv3d


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    ks = _norm_tuple(kernel_size, 3)
    st = _norm_tuple(stride if stride is not None else kernel_size, 3)
    return _max_pool_forward(x, ks, st, _norm_tuple(padding, 3), 3)


class functional:
    """paddle.sparse.nn.functional namespace."""
    from . import (  # noqa: F401
        relu, relu6, leaky_relu, softmax,
    )
    conv2d = staticmethod(conv2d)
    conv3d = staticmethod(conv3d)
    subm_conv2d = staticmethod(subm_conv2d)
    subm_conv3d = staticmethod(subm_conv3d)
    # igemm is an execution strategy in the reference, not a semantic — the
    # module-level functions are already aliased; avoid re-wrapping the
    # class-local staticmethod objects
    subm_conv2d_igemm = subm_conv2d
    subm_conv3d_igemm = subm_conv3d
    max_pool3d = staticmethod(max_pool3d)

    @staticmethod
    def attention(query, key, value, sparse_mask, key_padding_mask=None,
                  attn_mask=None, name=None):
        """Sparse-pattern attention (reference sparse/nn/functional/transformer.py):
        scores computed only at mask sites via SDDMM, row-softmax, then spmm."""
        from . import masked_matmul, matmul as sp_matmul, softmax as sp_softmax
        import math as _math
        if len(query.shape) != 2:
            raise ValueError(
                "sparse attention operates on 2-D (seq, head_dim) operands; vmap or "
                "loop per head for batched input")
        d = query.shape[-1]

        def scale_fn(q):
            return q / _math.sqrt(d)

        q_scaled = dispatch(scale_fn, (query,), {}, name="attn_scale")
        k_t = dispatch(lambda k: jnp.swapaxes(k, -1, -2), (key,), {}, name="attn_kT")
        scores = masked_matmul(q_scaled, k_t, sparse_mask)
        if attn_mask is not None or key_padding_mask is not None:
            rows = jnp.asarray(scores._indices[0])
            cols = jnp.asarray(scores._indices[1])

            def add_masks(v, am, kpm):
                if am is not None:
                    v = v + am[rows, cols]
                if kpm is not None:
                    if jnp.issubdtype(kpm.dtype, jnp.floating):
                        v = v + kpm[cols]  # additive float mask
                    else:
                        # 0/False at padded keys → -inf score
                        v = jnp.where(kpm[cols] > 0, v,
                                      jnp.asarray(-jnp.inf, v.dtype))
                return v

            vals = dispatch(add_masks, (scores._values, attn_mask,
                                        key_padding_mask), {}, name="attn_masks")
            from . import SparseCooTensor as _Coo
            scores = _Coo(scores._indices, vals, scores._shape, scores._coalesced)
        probs = sp_softmax(scores)
        return sp_matmul(probs, value)
