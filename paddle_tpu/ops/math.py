"""Elementwise + reduction math ops (paddle.tensor.math analog).

Reference: python/paddle/tensor/math.py dispatching _C_ops.* into phi kernels
(paddle/phi/kernels/elementwise_*.h, reduce_*.h). Every op here is one jnp/lax
expression; XLA fuses chains of them into single TPU kernels, which replaces the
reference's hand-fused elementwise CUDA kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, dispatch, register_op


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _make_binary(name, fn):
    def op(x, y, name_arg=None):
        return dispatch(fn, (x, y), {}, name=name)
    op.__name__ = name
    return op


def _make_unary(name, fn):
    def op(x, name_arg=None):
        return dispatch(fn, (x,), {}, name=name)
    op.__name__ = name
    return op


# -- binary elementwise -------------------------------------------------------
add = _make_binary("add", jnp.add)
subtract = _make_binary("subtract", jnp.subtract)
multiply = _make_binary("multiply", jnp.multiply)
divide = _make_binary("divide", jnp.true_divide)
floor_divide = _make_binary("floor_divide", jnp.floor_divide)
remainder = _make_binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = _make_binary("pow", jnp.power)
maximum = _make_binary("maximum", jnp.maximum)
minimum = _make_binary("minimum", jnp.minimum)
fmax = _make_binary("fmax", jnp.fmax)
fmin = _make_binary("fmin", jnp.fmin)
atan2 = _make_binary("atan2", jnp.arctan2)
logaddexp = _make_binary("logaddexp", jnp.logaddexp)
hypot = _make_binary("hypot", lambda x, y: jnp.sqrt(x * x + y * y))
copysign = _make_binary("copysign", jnp.copysign)
heaviside = _make_binary("heaviside", jnp.heaviside)
gcd = _make_binary("gcd", jnp.gcd)
lcm = _make_binary("lcm", jnp.lcm)
# paddle accepts a float exponent tensor (frexp returns one); jnp needs int
ldexp = _make_binary("ldexp",
                     lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)))
nextafter = _make_binary("nextafter", jnp.nextafter)
inner = _make_binary("inner", jnp.inner)
outer = _make_binary("outer", jnp.outer)
kron = _make_binary("kron", jnp.kron)



# -- unary elementwise --------------------------------------------------------
exp = _make_unary("exp", jnp.exp)
expm1 = _make_unary("expm1", jnp.expm1)
log = _make_unary("log", jnp.log)
log2 = _make_unary("log2", jnp.log2)
log10 = _make_unary("log10", jnp.log10)
log1p = _make_unary("log1p", jnp.log1p)
sqrt = _make_unary("sqrt", jnp.sqrt)
rsqrt = _make_unary("rsqrt", jax.lax.rsqrt)
abs = _make_unary("abs", jnp.abs)
neg = _make_unary("neg", jnp.negative)
sign = _make_unary("sign", jnp.sign)
floor = _make_unary("floor", jnp.floor)
ceil = _make_unary("ceil", jnp.ceil)
round = _make_unary("round", jnp.round)
trunc = _make_unary("trunc", jnp.trunc)
frac = _make_unary("frac", lambda x: x - jnp.trunc(x))
sin = _make_unary("sin", jnp.sin)
cos = _make_unary("cos", jnp.cos)
tan = _make_unary("tan", jnp.tan)
asin = _make_unary("asin", jnp.arcsin)
acos = _make_unary("acos", jnp.arccos)
atan = _make_unary("atan", jnp.arctan)
sinh = _make_unary("sinh", jnp.sinh)
cosh = _make_unary("cosh", jnp.cosh)
tanh = _make_unary("tanh", jnp.tanh)
asinh = _make_unary("asinh", jnp.arcsinh)
acosh = _make_unary("acosh", jnp.arccosh)
atanh = _make_unary("atanh", jnp.arctanh)
reciprocal = _make_unary("reciprocal", jnp.reciprocal)
square = _make_unary("square", jnp.square)
erf = _make_unary("erf", jax.scipy.special.erf)
erfinv = _make_unary("erfinv", jax.scipy.special.erfinv)
lgamma = _make_unary("lgamma", jax.scipy.special.gammaln)
digamma = _make_unary("digamma", jax.scipy.special.digamma)
i0 = _make_unary("i0", jax.scipy.special.i0)
i0e = _make_unary("i0e", jax.scipy.special.i0e)
i1 = _make_unary("i1", jax.scipy.special.i1)
i1e = _make_unary("i1e", jax.scipy.special.i1e)
angle = _make_unary("angle", jnp.angle)
conj = _make_unary("conj", jnp.conj)
real = _make_unary("real", jnp.real)
imag = _make_unary("imag", jnp.imag)
rad2deg = _make_unary("rad2deg", jnp.rad2deg)
deg2rad = _make_unary("deg2rad", jnp.deg2rad)
sigmoid = _make_unary("sigmoid", jax.nn.sigmoid)
isnan = _make_unary("isnan", jnp.isnan)
isinf = _make_unary("isinf", jnp.isinf)
isfinite = _make_unary("isfinite", jnp.isfinite)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return dispatch(lambda v: scale_b * jnp.tanh(scale_a * v), (x,), {}, name="stanh")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    def fn(v, s, b):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out.astype(v.dtype)
    return dispatch(fn, (x, scale, bias), {}, name="scale")


def clip(x, min=None, max=None):
    def fn(v, lo, hi):
        return jnp.clip(v, lo, hi)
    return dispatch(fn, (x, min, max), {}, name="clip")


def lerp(x, y, weight):
    return dispatch(lambda a, b, w: a + w * (b - a), (x, y, weight), {}, name="lerp")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return dispatch(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf),
                    (x,), {}, name="nan_to_num")


def increment(x, value=1.0):
    x._value = x._value + jnp.asarray(value, x._value.dtype)
    return x


def add_n(inputs):
    return dispatch(lambda *vs: sum_arrays(vs), tuple(inputs), {}, name="add_n")


def sum_arrays(vs):
    out = vs[0]
    for v in vs[1:]:
        out = out + v
    return out


def diff(x, n=1, axis=-1, prepend=None, append=None):
    args = (x,) + ((prepend,) if prepend is not None else ()) + \
        ((append,) if append is not None else ())

    def fn(v, *rest):
        p = rest[0] if prepend is not None else None
        a = rest[-1] if append is not None else None
        return jnp.diff(v, n=int(n), axis=int(axis), prepend=p, append=a)
    return dispatch(fn, args, {}, name="diff")


# -- reductions ---------------------------------------------------------------

def sum(x, axis=None, dtype=None, keepdim=False):
    d = dtypes.convert_dtype(dtype) if dtype is not None else None

    def fn(v):
        dd = d
        if dd is None and (v.dtype == jnp.bool_ or jnp.issubdtype(v.dtype, jnp.integer)):
            dd = jnp.int64
        return jnp.sum(v, axis=_axis(axis), dtype=dd, keepdims=keepdim)
    return dispatch(fn, (x,), {}, name="sum")


def mean(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {}, name="mean")


def prod(x, axis=None, keepdim=False, dtype=None):
    d = dtypes.convert_dtype(dtype) if dtype is not None else None
    return dispatch(lambda v: jnp.prod(v, axis=_axis(axis), dtype=d, keepdims=keepdim),
                    (x,), {}, name="prod")


def max(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {}, name="max")


def min(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {}, name="min")


amax = max
amin = min


def std(x, axis=None, unbiased=True, keepdim=False):
    return dispatch(lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                      keepdims=keepdim), (x,), {}, name="std")


def var(x, axis=None, unbiased=True, keepdim=False):
    return dispatch(lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                      keepdims=keepdim), (x,), {}, name="var")


def median(x, axis=None, keepdim=False, mode="avg"):
    def fn(v):
        if mode == "min" and axis is not None:
            # paddle's 'min' mode returns lower median
            n = v.shape[_axis(axis)]
            sorted_v = jnp.sort(v, axis=_axis(axis))
            idx = (n - 1) // 2
            out = jnp.take(sorted_v, idx, axis=_axis(axis))
            return jnp.expand_dims(out, _axis(axis)) if keepdim else out
        return jnp.median(v, axis=_axis(axis), keepdims=keepdim)
    return dispatch(fn, (x,), {}, name="median")


def nanmedian(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {}, name="nanmedian")


def nansum(x, axis=None, dtype=None, keepdim=False):
    d = dtypes.convert_dtype(dtype) if dtype is not None else None
    return dispatch(lambda v: jnp.nansum(v, axis=_axis(axis), dtype=d, keepdims=keepdim),
                    (x,), {}, name="nansum")


def nanmean(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {}, name="nanmean")


def quantile(x, q, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.quantile(v, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim), (x,), {}, name="quantile")


def logsumexp(x, axis=None, keepdim=False):
    return dispatch(lambda v: jax.scipy.special.logsumexp(v, axis=_axis(axis),
                                                          keepdims=keepdim),
                    (x,), {}, name="logsumexp")


def all(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {}, name="all")


def any(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {}, name="any")


def count_nonzero(x, axis=None, keepdim=False):
    return dispatch(lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim)
                    .astype(jnp.int64), (x,), {}, name="count_nonzero")


# -- scans --------------------------------------------------------------------

def cumsum(x, axis=None, dtype=None):
    d = dtypes.convert_dtype(dtype) if dtype is not None else None

    def fn(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=d)
        return jnp.cumsum(v, axis=int(axis), dtype=d)
    return dispatch(fn, (x,), {}, name="cumsum")


def cumprod(x, dim=None, dtype=None):
    d = dtypes.convert_dtype(dtype) if dtype is not None else None

    def fn(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1), dtype=d)
        return jnp.cumprod(v, axis=int(dim), dtype=d)
    return dispatch(fn, (x,), {}, name="cumprod")


def cummax(x, axis=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        out = jax.lax.associative_scan(jnp.maximum, vv, axis=a)
        idx_in = jnp.arange(vv.shape[a])
        shape = [1] * vv.ndim
        shape[a] = vv.shape[a]
        idx_b = jnp.broadcast_to(idx_in.reshape(shape), vv.shape)

        def take_max(p, q):
            pv, pi = p
            qv, qi = q
            keep = qv >= pv
            return jnp.where(keep, qv, pv), jnp.where(keep, qi, pi)
        mv, mi = jax.lax.associative_scan(take_max, (vv, idx_b), axis=a)
        return mv, mi.astype(jnp.int64)
    return dispatch(fn, (x,), {}, name="cummax")


def cummin(x, axis=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        idx_in = jnp.arange(vv.shape[a])
        shape = [1] * vv.ndim
        shape[a] = vv.shape[a]
        idx_b = jnp.broadcast_to(idx_in.reshape(shape), vv.shape)

        def take_min(p, q):
            pv, pi = p
            qv, qi = q
            keep = qv <= pv
            return jnp.where(keep, qv, pv), jnp.where(keep, qi, pi)
        mv, mi = jax.lax.associative_scan(take_min, (vv, idx_b), axis=a)
        return mv, mi.astype(jnp.int64)
    return dispatch(fn, (x,), {}, name="cummin")


def logcumsumexp(x, axis=None):
    def fn(v):
        a = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v

        def comb(p, q):
            return jnp.logaddexp(p, q)
        return jax.lax.associative_scan(comb, vv, axis=a)
    return dispatch(fn, (x,), {}, name="logcumsumexp")


# -- matrix-ish helpers in paddle.tensor.math --------------------------------

def addmm(input, x, y, beta=1.0, alpha=1.0):
    return dispatch(lambda i, a, b: beta * i + alpha * (a @ b), (input, x, y), {},
                    name="addmm")


def trace(x, offset=0, axis1=0, axis2=1):
    return dispatch(lambda v: jnp.trace(v, offset=int(offset), axis1=int(axis1),
                                        axis2=int(axis2)), (x,), {}, name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1):
    return dispatch(lambda v: jnp.diagonal(v, offset=int(offset), axis1=int(axis1),
                                           axis2=int(axis2)), (x,), {}, name="diagonal")


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def fn(v):
        n = v.shape[-1] + builtins_abs(int(offset))
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        if offset >= 0:
            out = out.at[..., idx, idx + offset].set(v)
        else:
            out = out.at[..., idx - offset, idx].set(v)
        if (int(dim1), int(dim2)) not in ((-2, -1), (v.ndim - 1, v.ndim)):
            out = jnp.moveaxis(out, (-2, -1), (int(dim1), int(dim2)))
        return out
    return dispatch(fn, (x,), {}, name="diag_embed")


import builtins
builtins_abs = builtins.abs
