"""Linear algebra ops (paddle.tensor.linalg + paddle.linalg analog).

Reference: python/paddle/tensor/linalg.py (matmul at :220) → phi kernels → cuBLAS/
cuSOLVER. TPU-native: matmul lowers straight to the MXU via jnp; decompositions ride
jax.numpy.linalg/jax.scipy (XLA custom calls or QR-based algorithms on TPU).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """paddle.matmul (reference: python/paddle/tensor/linalg.py:220)."""
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return dispatch(fn, (x, y), {}, name="matmul")


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return dispatch(jnp.matmul, (x, y), {}, name="bmm")


def mv(x, vec):
    return dispatch(jnp.matmul, (x, vec), {}, name="mv")


def dot(x, y):
    def fn(a, b):
        return jnp.sum(a * b, axis=-1)
    return dispatch(fn, (x, y), {}, name="dot")


def cross(x, y, axis=9):
    def fn(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=int(ax))
    return dispatch(fn, (x, y), {}, name="cross")


def norm(x, p=None, axis=None, keepdim=False):
    def fn(v):
        pp = p
        if pp is None:
            pp = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2
        if axis is None:
            flat = v.reshape(-1)
            if pp == "fro" or pp == 2:
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(flat))))
            if pp == np.inf or pp == "inf":
                return jnp.max(jnp.abs(flat))
            if pp == -np.inf:
                return jnp.min(jnp.abs(flat))
            if pp == 0:
                return jnp.sum(flat != 0).astype(v.dtype)
            if pp == 1:
                return jnp.sum(jnp.abs(flat))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(flat), pp)), 1.0 / pp)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else int(axis)
        if pp == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(v)), axis=ax, keepdims=keepdim))
        if pp == "nuc":
            return jnp.linalg.norm(v, ord="nuc", axis=ax, keepdims=keepdim)
        if pp == np.inf or pp == "inf":
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp == -np.inf:
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        if pp == 1:
            return jnp.sum(jnp.abs(v), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), pp), axis=ax, keepdims=keepdim),
                         1.0 / pp)
    return dispatch(fn, (x,), {}, name="norm")


def vector_norm(x, p=2, axis=None, keepdim=False):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return dispatch(lambda v: jnp.linalg.norm(v, ord=p, axis=tuple(axis),
                                              keepdims=keepdim), (x,), {},
                    name="matrix_norm")


def dist(x, y, p=2):
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if p == np.inf:
            return jnp.max(jnp.abs(d))
        if p == -np.inf:
            return jnp.min(jnp.abs(d))
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)
    return dispatch(fn, (x, y), {}, name="dist")


def cholesky(x, upper=False):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return dispatch(fn, (x,), {}, name="cholesky")


def cholesky_solve(x, y, upper=False):
    def fn(b, L):
        Lm = jnp.swapaxes(L, -1, -2).conj() if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(Lm, -1, -2).conj(), z,
                                                 lower=False)
    return dispatch(fn, (x, y), {}, name="cholesky_solve")


def inverse(x):
    return dispatch(jnp.linalg.inv, (x,), {}, name="inverse")


inv = inverse


def det(x):
    return dispatch(jnp.linalg.det, (x,), {}, name="det")


def slogdet(x):
    def fn(v):
        sign, logabs = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logabs])
    return dispatch(fn, (x,), {}, name="slogdet")


def svd(x, full_matrices=False):
    def fn(v):
        u, s, vh = jnp.linalg.svd(v, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2).conj()
    return dispatch(fn, (x,), {}, name="svd")


def qr(x, mode="reduced"):
    return dispatch(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), (x,), {}, name="qr")


def eig(x):
    # general eig is CPU-only in XLA; run via numpy (eager-only, like reference CPU fallback)
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigh(x, UPLO="L"):
    return dispatch(lambda v: tuple(jnp.linalg.eigh(v, symmetrize_input=True)),
                    (x,), {}, name="eigh")


def eigvals(x):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def eigvalsh(x, UPLO="L"):
    return dispatch(jnp.linalg.eigvalsh, (x,), {}, name="eigvalsh")


def matrix_power(x, n):
    return dispatch(lambda v: jnp.linalg.matrix_power(v, int(n)), (x,), {},
                    name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False):
    return dispatch(lambda v: jnp.linalg.matrix_rank(v, tol=tol), (x,), {},
                    name="matrix_rank")


def solve(x, y):
    def fn(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)
    return dispatch(fn, (x, y), {}, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return dispatch(fn, (x, y), {}, name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return dispatch(fn, (x, y), {}, name="lstsq")


def pinv(x, rcond=1e-15, hermitian=False):
    return dispatch(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian),
                    (x,), {}, name="pinv")


def lu(x, pivot=True):
    def fn(v):
        lu_mat, piv = jax.scipy.linalg.lu_factor(v)
        return lu_mat, (piv + 1).astype(jnp.int32)
    return dispatch(fn, (x,), {}, name="lu")


def cond(x, p=None):
    return dispatch(lambda v: jnp.linalg.cond(v, p=p), (x,), {}, name="cond")


def multi_dot(tensors):
    return dispatch(lambda *vs: jnp.linalg.multi_dot(vs), tuple(tensors), {},
                    name="multi_dot")


def corrcoef(x, rowvar=True):
    return dispatch(lambda v: jnp.corrcoef(v, rowvar=rowvar), (x,), {}, name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    def fn(v):
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0)
    return dispatch(fn, (x,), {}, name="cov")


def householder_product(x, tau):
    def fn(a, t):
        *batch, m, n = a.shape
        def one(a2, t2):
            q = jnp.eye(m, dtype=a2.dtype)
            for i in range(t2.shape[0]):
                v = jnp.concatenate([jnp.zeros(i, a2.dtype), jnp.ones(1, a2.dtype),
                                     a2[i + 1:, i]])
                q = q - t2[i] * (q @ jnp.outer(v, v))
            return q[:, :n]
        if batch:
            flat_a = a.reshape((-1, m, n))
            flat_t = t.reshape((-1, t.shape[-1]))
            outs = jax.vmap(one)(flat_a, flat_t)
            return outs.reshape(*batch, m, n)
        return one(a, t)
    return dispatch(fn, (x, tau), {}, name="householder_product")


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A from its Cholesky factor (reference: tensor/linalg.py
    cholesky_inverse → cholesky_solve against identity)."""
    def fn(f):
        eye = jnp.eye(f.shape[-1], dtype=f.dtype)
        if upper:
            # A = U^T U ; solve U^T U X = I
            z = jax.scipy.linalg.solve_triangular(f, eye, trans=1, lower=False)
            return jax.scipy.linalg.solve_triangular(f, z, lower=False)
        z = jax.scipy.linalg.solve_triangular(f, eye, lower=True)
        return jax.scipy.linalg.solve_triangular(f, z, trans=1, lower=True)
    return dispatch(fn, (x,), {}, name="cholesky_inverse")


def vecdot(x, y, axis=-1, name=None):
    """reference: tensor/linalg.py vecdot — conj(x)·y along axis."""
    return dispatch(lambda a, b: jnp.sum(jnp.conj(a) * b, axis=axis),
                    (x, y), {}, name="vecdot")


def matrix_transpose(x, name=None):
    return dispatch(lambda v: jnp.swapaxes(v, -2, -1), (x,), {},
                    name="matrix_transpose")


def svdvals(x, name=None):
    return dispatch(lambda v: jnp.linalg.svd(v, compute_uv=False), (x,), {},
                    name="svdvals")


def matrix_exp(x, name=None):
    return dispatch(jax.scipy.linalg.expm, (x,), {}, name="matrix_exp")


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack the packed LU factorization (reference: tensor/linalg.py
    lu_unpack): returns (P, L, U) from lu() outputs."""
    def fn(lu_data, pivots):
        m, n = lu_data.shape[-2], lu_data.shape[-1]
        k = min(m, n)
        # L: unit lower-trapezoid (m, k); U: upper-trapezoid (k, n)
        eyek = jnp.eye(m, k, dtype=lu_data.dtype)
        L = jnp.tril(lu_data[..., :, :k], -1) + eyek
        U = jnp.triu(lu_data[..., :k, :])
        # pivots (1-based sequential swaps) -> permutation matrix
        def perm_from_pivots(piv):
            perm = jnp.arange(m)
            def body(i, p):
                j = piv[i] - 1
                pi, pj = p[i], p[j]
                return p.at[i].set(pj).at[j].set(pi)
            perm = jax.lax.fori_loop(0, piv.shape[0], body, perm)
            return jnp.eye(m, dtype=lu_data.dtype)[:, perm].T
        if pivots.ndim == 1:
            P = perm_from_pivots(pivots).T
        else:
            P = jax.vmap(perm_from_pivots)(
                pivots.reshape(-1, pivots.shape[-1]))
            P = jnp.swapaxes(P, -2, -1).reshape(lu_data.shape[:-2] + (m, m))
        return P, L, U
    return dispatch(fn, (x, y), {}, name="lu_unpack")


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by the full Q from Householder reflectors (reference:
    tensor/linalg.py ormqr / LAPACK ormqr): applies each reflector
    H_k = I - tau_k v_k v_k^T to y without materializing Q — rank-1 updates,
    O(m·n·k) like the LAPACK path."""
    def fn(a, t, other):
        m = a.shape[-2]
        k = t.shape[-1]
        rows = jnp.arange(m)

        def reflector(i):
            v = jnp.where(rows < i, 0.0, jnp.where(rows == i, 1.0, a[:, i]))
            return v

        def apply_left(o, order):
            for i in order:
                v = reflector(i)
                o = o - t[i] * jnp.outer(v, v @ o)
            return o

        def apply_right(o, order):
            for i in order:
                v = reflector(i)
                o = o - t[i] * jnp.outer(o @ v, v)
            return o

        # Q = H_0 H_1 ... H_{k-1}; Q @ y applies H_{k-1} first
        if left:
            order = range(k) if transpose else range(k - 1, -1, -1)
            return apply_left(other, order)
        order = range(k - 1, -1, -1) if transpose else range(k)
        return apply_right(other, order)
    return dispatch(fn, (x, tau, y), {}, name="ormqr")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference: tensor/linalg.py svd_lowrank —
    Halko et al. subspace iteration, same algorithm torch uses)."""
    from ..core import random as _random
    key = _random.next_key()

    def fn(a, *m):
        a2 = a - m[0] if m else a
        n = a2.shape[-1]
        g = jax.random.normal(key, a2.shape[:-2] + (n, q), a2.dtype)
        y = a2 @ g
        for _ in range(niter):
            y = a2 @ (jnp.swapaxes(a2, -2, -1) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(qmat, -2, -1) @ a2
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u, s, jnp.swapaxes(vh, -2, -1)
    args = (x,) + ((M,) if M is not None else ())
    return dispatch(fn, args, {}, name="svd_lowrank")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference: tensor/linalg.py pca_lowrank."""
    import paddle_tpu as _paddle
    rank = q if q is not None else min(6, x.shape[-2], x.shape[-1])
    if center:
        mean = _paddle.mean(x, axis=-2, keepdim=True)
        x = x - mean
    return svd_lowrank(x, q=rank, niter=niter)


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, scale=1.0,
                            output_dtype="float16", name=None):
    """fp8 e4m3 GEMM with half/bf16 output (reference: tensor/linalg.py
    fp8_fp8_half_gemm_fused → cutlass fp8 kernel). TPU: XLA handles
    float8_e4m3fn dot with bf16 accumulation natively on v5+."""
    from ..core.dtype import convert_dtype
    out_dt = convert_dtype(output_dtype)

    def fn(a, b, *bi):
        aa = jnp.swapaxes(a, -2, -1) if transpose_x else a
        bb = jnp.swapaxes(b, -2, -1) if transpose_y else b
        out = jnp.matmul(aa.astype(jnp.bfloat16), bb.astype(jnp.bfloat16))
        out = out * scale
        if bi:
            out = out + bi[0].astype(out.dtype)
        return out.astype(out_dt)
    args = (x, y) + ((bias,) if bias is not None else ())
    return dispatch(fn, args, {}, name="fp8_fp8_half_gemm_fused")
