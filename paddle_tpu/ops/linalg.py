"""Linear algebra ops (paddle.tensor.linalg + paddle.linalg analog).

Reference: python/paddle/tensor/linalg.py (matmul at :220) → phi kernels → cuBLAS/
cuSOLVER. TPU-native: matmul lowers straight to the MXU via jnp; decompositions ride
jax.numpy.linalg/jax.scipy (XLA custom calls or QR-based algorithms on TPU).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """paddle.matmul (reference: python/paddle/tensor/linalg.py:220)."""
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return dispatch(fn, (x, y), {}, name="matmul")


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return dispatch(jnp.matmul, (x, y), {}, name="bmm")


def mv(x, vec):
    return dispatch(jnp.matmul, (x, vec), {}, name="mv")


def dot(x, y):
    def fn(a, b):
        return jnp.sum(a * b, axis=-1)
    return dispatch(fn, (x, y), {}, name="dot")


def cross(x, y, axis=9):
    def fn(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=int(ax))
    return dispatch(fn, (x, y), {}, name="cross")


def norm(x, p=None, axis=None, keepdim=False):
    def fn(v):
        pp = p
        if pp is None:
            pp = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2
        if axis is None:
            flat = v.reshape(-1)
            if pp == "fro" or pp == 2:
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(flat))))
            if pp == np.inf or pp == "inf":
                return jnp.max(jnp.abs(flat))
            if pp == -np.inf:
                return jnp.min(jnp.abs(flat))
            if pp == 0:
                return jnp.sum(flat != 0).astype(v.dtype)
            if pp == 1:
                return jnp.sum(jnp.abs(flat))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(flat), pp)), 1.0 / pp)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else int(axis)
        if pp == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(v)), axis=ax, keepdims=keepdim))
        if pp == "nuc":
            return jnp.linalg.norm(v, ord="nuc", axis=ax, keepdims=keepdim)
        if pp == np.inf or pp == "inf":
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp == -np.inf:
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        if pp == 1:
            return jnp.sum(jnp.abs(v), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), pp), axis=ax, keepdims=keepdim),
                         1.0 / pp)
    return dispatch(fn, (x,), {}, name="norm")


def vector_norm(x, p=2, axis=None, keepdim=False):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return dispatch(lambda v: jnp.linalg.norm(v, ord=p, axis=tuple(axis),
                                              keepdims=keepdim), (x,), {},
                    name="matrix_norm")


def dist(x, y, p=2):
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if p == np.inf:
            return jnp.max(jnp.abs(d))
        if p == -np.inf:
            return jnp.min(jnp.abs(d))
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)
    return dispatch(fn, (x, y), {}, name="dist")


def cholesky(x, upper=False):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return dispatch(fn, (x,), {}, name="cholesky")


def cholesky_solve(x, y, upper=False):
    def fn(b, L):
        Lm = jnp.swapaxes(L, -1, -2).conj() if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(Lm, -1, -2).conj(), z,
                                                 lower=False)
    return dispatch(fn, (x, y), {}, name="cholesky_solve")


def inverse(x):
    return dispatch(jnp.linalg.inv, (x,), {}, name="inverse")


inv = inverse


def det(x):
    return dispatch(jnp.linalg.det, (x,), {}, name="det")


def slogdet(x):
    def fn(v):
        sign, logabs = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logabs])
    return dispatch(fn, (x,), {}, name="slogdet")


def svd(x, full_matrices=False):
    def fn(v):
        u, s, vh = jnp.linalg.svd(v, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2).conj()
    return dispatch(fn, (x,), {}, name="svd")


def qr(x, mode="reduced"):
    return dispatch(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), (x,), {}, name="qr")


def eig(x):
    # general eig is CPU-only in XLA; run via numpy (eager-only, like reference CPU fallback)
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigh(x, UPLO="L"):
    return dispatch(lambda v: tuple(jnp.linalg.eigh(v, symmetrize_input=True)),
                    (x,), {}, name="eigh")


def eigvals(x):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def eigvalsh(x, UPLO="L"):
    return dispatch(jnp.linalg.eigvalsh, (x,), {}, name="eigvalsh")


def matrix_power(x, n):
    return dispatch(lambda v: jnp.linalg.matrix_power(v, int(n)), (x,), {},
                    name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False):
    return dispatch(lambda v: jnp.linalg.matrix_rank(v, tol=tol), (x,), {},
                    name="matrix_rank")


def solve(x, y):
    def fn(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)
    return dispatch(fn, (x, y), {}, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return dispatch(fn, (x, y), {}, name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return dispatch(fn, (x, y), {}, name="lstsq")


def pinv(x, rcond=1e-15, hermitian=False):
    return dispatch(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian),
                    (x,), {}, name="pinv")


def lu(x, pivot=True):
    def fn(v):
        lu_mat, piv = jax.scipy.linalg.lu_factor(v)
        return lu_mat, (piv + 1).astype(jnp.int32)
    return dispatch(fn, (x,), {}, name="lu")


def cond(x, p=None):
    return dispatch(lambda v: jnp.linalg.cond(v, p=p), (x,), {}, name="cond")


def multi_dot(tensors):
    return dispatch(lambda *vs: jnp.linalg.multi_dot(vs), tuple(tensors), {},
                    name="multi_dot")


def corrcoef(x, rowvar=True):
    return dispatch(lambda v: jnp.corrcoef(v, rowvar=rowvar), (x,), {}, name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    def fn(v):
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0)
    return dispatch(fn, (x,), {}, name="cov")


def householder_product(x, tau):
    def fn(a, t):
        *batch, m, n = a.shape
        def one(a2, t2):
            q = jnp.eye(m, dtype=a2.dtype)
            for i in range(t2.shape[0]):
                v = jnp.concatenate([jnp.zeros(i, a2.dtype), jnp.ones(1, a2.dtype),
                                     a2[i + 1:, i]])
                q = q - t2[i] * (q @ jnp.outer(v, v))
            return q[:, :n]
        if batch:
            flat_a = a.reshape((-1, m, n))
            flat_t = t.reshape((-1, t.shape[-1]))
            outs = jax.vmap(one)(flat_a, flat_t)
            return outs.reshape(*batch, m, n)
        return one(a, t)
    return dispatch(fn, (x, tau), {}, name="householder_product")
