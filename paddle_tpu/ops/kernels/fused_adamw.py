"""Pallas TPU fused AdamW update — single-pass multi-precision step.

Reference analog: phi's fused_adam / multi_tensor adam kernels
(paddle/phi/kernels/fused_adam_kernel.h) that the reference optimizer uses to
avoid per-tensor kernel-launch and read-modify-write traffic. On TPU the
bottleneck is HBM bandwidth: the XLA lowering of the update chain re-reads the
fp32 moment/master buffers across fusion boundaries, sustaining only ~½ of
peak bandwidth. This kernel does the whole update in ONE pass per block —
read g(bf16), m, v, master(fp32); write m, v, master, p(bf16) — which is the
minimum possible traffic (~24.5 GB for a 880M-param model vs ~45 GB observed
from the XLA path).

Math (AdamW, decoupled weight decay, bias-corrected):
    m = b1*m + (1-b1)*g
    v = b2*v + (1-b2)*g^2
    update = (m/bc1) / (sqrt(v)/sqrt(bc2) + eps)
    master = master - lr*update - lr*wd*master
    p_bf16 = cast(master)
Scalars alpha=lr/bc1, c2=1/sqrt(bc2), lr, lr*wd arrive via SMEM so one
compiled kernel serves every step.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


Z = np.int32(0)  # i32 index-map literal (x64 is on)


def _interpret():
    return jax.default_backend() not in ("tpu",)


def _adamw_kernel(scal_ref, g_ref, m_ref, v_ref, mw_ref,
                  om_ref, ov_ref, omw_ref, op_ref, *, beta1, beta2, eps):
    alpha = scal_ref[0, 0]  # lr / bias_correction1
    c2 = scal_ref[0, 1]     # 1 / sqrt(bias_correction2)
    lrwd = scal_ref[0, 2]   # lr * weight_decay (0 when decay masked off)
    g = g_ref[...].astype(jnp.float32)
    m = beta1 * m_ref[...] + (1.0 - beta1) * g
    v = beta2 * v_ref[...] + (1.0 - beta2) * (g * g)
    denom = jnp.sqrt(v) * c2 + eps
    mw = mw_ref[...]
    new_mw = mw - alpha * (m / denom) - lrwd * mw
    om_ref[...] = m
    ov_ref[...] = v
    omw_ref[...] = new_mw
    op_ref[...] = new_mw.astype(op_ref.dtype)


def _pick_block(rows, cols):
    """Rows per block: 9 live fp32-sized buffers of (block_r, cols) must fit
    the ~16 MB scoped-VMEM budget; stay a multiple of 8 (f32 sublane)."""
    # pallas double-buffers every in/out block, so the scoped-VMEM footprint
    # is ~2x the 9 live fp32-sized buffers — budget 4 MB of logical blocks
    target = 4 * 1024 * 1024 // (9 * 4 * max(cols, 1))
    br = max(8, min(rows, (target // 8) * 8))
    while rows % br:
        br -= 8
        if br <= 0:
            return rows
    return br


def _fused_adamw_2d(scalars, g, m, v, mw, *, beta1, beta2, eps, out_dtype):
    rows, cols = m.shape
    br = _pick_block(rows, cols)
    grid = (rows // br,)

    Z = np.int32(0)

    def idx(i):
        return (i, Z)

    bs = lambda: pl.BlockSpec((br, cols), idx)
    scal_spec = pl.BlockSpec((1, 3), lambda i: (Z, Z))
    out_shapes = (
        jax.ShapeDtypeStruct((rows, cols), jnp.float32),  # m
        jax.ShapeDtypeStruct((rows, cols), jnp.float32),  # v
        jax.ShapeDtypeStruct((rows, cols), jnp.float32),  # master
        jax.ShapeDtypeStruct((rows, cols), out_dtype),    # bf16/low param
    )
    kernel = functools.partial(_adamw_kernel, beta1=float(beta1),
                               beta2=float(beta2), eps=float(eps))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[scal_spec, bs(), bs(), bs(), bs()],
        out_specs=(bs(), bs(), bs(), bs()),
        out_shape=out_shapes,
        # m/v/master update in place — no state copies in HBM (the outer
        # train step donates these buffers)
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=_interpret(),
    )(scalars, g, m, v, mw)


def _tile_plan(shape):
    """(rows, cols) 2-D factorization for the kernel, or None when the shape
    cannot be tiled within the VMEM budget. Pure shape computation — callers
    (incl. the shard_map wrapper) can pre-flight before committing to the
    pallas path."""
    n = int(np.prod(shape)) if shape else 1
    # factor into (rows, cols) with cols a multiple of 128 when possible
    if len(shape) >= 2:
        rows = int(shape[0])
        cols = n // rows
    else:
        cols = min(n, 131072)
        while n % cols:
            cols //= 2
        cols = max(cols, 1)
        rows = n // cols
    if rows * cols != n or (rows % 8 != 0 and rows != 1):
        # odd leading dim: try to refactor n into tileable (rows, cols)
        cols = 1
        for c in (131072, 65536, 32768, 16384, 8192, 4096, 2048, 1024, 512,
                  256, 128):
            if n % c == 0 and (n // c) % 8 == 0:
                cols = c
                break
        if cols > 1:
            rows = n // cols
        else:
            rows, cols = 1, n
    # unified VMEM guard: 9 live fp32-sized buffers, double-buffered by
    # pallas, must stay within the ~16 MB scoped budget. _pick_block can't go
    # below 8 rows, so wide-column tensors can still exceed it — refuse and
    # let the generic XLA update handle those.
    br = _pick_block(rows, cols)
    if br * cols > (4 * 1024 * 1024) // (9 * 4):
        return None
    return rows, cols


def fused_adamw_update(p_low, g, m, v, master, lr, step, *, beta1=0.9,
                       beta2=0.999, eps=1e-8, weight_decay=0.0,
                       apply_decay=True):
    """One fused AdamW step for a low-precision param with fp32 master/moments.

    Returns (new_p_low, new_m, new_v, new_master), or None when the shape
    cannot be tiled within the VMEM budget (caller falls back to the generic
    XLA update). All tensors keep their logical shape; internally flattened
    to 2-D blocks.
    """
    shape = m.shape
    plan = _tile_plan(shape)
    if plan is None:
        return None
    rows, cols = plan
    stepf = step.astype(jnp.float32)
    bc1 = 1.0 - jnp.power(beta1, stepf)
    bc2 = 1.0 - jnp.power(beta2, stepf)
    lr32 = lr.astype(jnp.float32)
    wd = lr32 * weight_decay if (weight_decay and apply_decay) else \
        jnp.zeros((), jnp.float32)
    scalars = jnp.stack([lr32 / bc1, 1.0 / jnp.sqrt(bc2), wd]) \
        .astype(jnp.float32).reshape(1, 3)

    g2 = g.reshape(rows, cols)
    m2 = m.reshape(rows, cols)
    v2 = v.reshape(rows, cols)
    mw2 = master.reshape(rows, cols)
    nm, nv, nmw, np_low = _fused_adamw_2d(
        scalars, g2, m2, v2, mw2, beta1=beta1, beta2=beta2, eps=eps,
        out_dtype=p_low.dtype)
    return (np_low.reshape(shape), nm.reshape(shape), nv.reshape(shape),
            nmw.reshape(shape))


def _local_shape(mesh, spec, shape):
    """Per-device shape of `shape` stored as PartitionSpec `spec`, or None if
    a sharded dim doesn't divide (caller falls back to the XLA update)."""
    spec_t = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    local = list(shape)
    for d, ax in enumerate(spec_t):
        if ax is None:
            continue
        n = 1
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            n *= mesh.shape[a]
        if local[d] % n:
            return None
        local[d] //= n
    return tuple(local)


def fused_adamw_update_sharded(mesh, spec, p_low, g, m, v, master, lr, step,
                               **kw):
    """Fused AdamW over SHARDED state: each device runs the single-pass pallas
    kernel on its local shard via shard_map (the update is elementwise, so no
    communication is needed inside). This is what lets ZeRO keep the fused
    optimizer — GSPMD can't partition a pallas_call, but it doesn't have to.

    Returns (new_p_low, new_m, new_v, new_master) or None when the local
    shard isn't tileable (caller falls back to the generic XLA update).
    Reference analog: the sharded fused update in
    fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:54.
    """
    local = _local_shape(mesh, spec, tuple(m.shape))
    if local is None or _tile_plan(local) is None:
        return None
    from jax.sharding import PartitionSpec
    ps = PartitionSpec(*(tuple(spec) + (None,) * (m.ndim - len(tuple(spec)))))
    rep = PartitionSpec()

    def local_update(p_l, g_l, m_l, v_l, mw_l, lr_s, step_s):
        return fused_adamw_update(p_l, g_l, m_l, v_l, mw_l, lr_s, step_s, **kw)

    f = jax.shard_map(local_update, mesh=mesh,
                      in_specs=(ps, ps, ps, ps, ps, rep, rep),
                      out_specs=(ps, ps, ps, ps), check_vma=False)
    return f(p_low, g, m, v, master, jnp.asarray(lr), jnp.asarray(step))


# ---------------------------------------------------------------------------
# master-weight-free AdamW with stochastic rounding
# ---------------------------------------------------------------------------

def _sr_round_bf16(x_f32, seed_i, base_idx):
    """Stochastically round fp32 -> bf16: add position-hashed uniform bits
    below the bf16 mantissa cut, then truncate. E[round(x)] == x, which is
    what lets bf16 params integrate small updates WITHOUT an fp32 master
    copy (the classic TPU trick; reference keeps fp32 masters instead)."""
    bits = jax.lax.bitcast_convert_type(x_f32, jnp.int32)
    h = base_idx * np.int32(-1640531527) + seed_i
    h = h ^ jax.lax.shift_right_logical(h, np.int32(16))
    h = h * np.int32(-2048144789)
    h = h ^ jax.lax.shift_right_logical(h, np.int32(13))
    h = h * np.int32(-1028477387)
    h = h ^ jax.lax.shift_right_logical(h, np.int32(16))
    r16 = h & np.int32(0xFFFF)
    rounded = (bits + r16) & np.int32(-65536)   # keep the top 16 bits
    return jax.lax.bitcast_convert_type(rounded, jnp.float32) \
        .astype(jnp.bfloat16)


def _adamw_sr_kernel(scal_ref, seed_ref, g_ref, p_ref, m_ref, v_ref,
                     om_ref, ov_ref, op_ref, *, beta1, beta2, eps, bi, cols):
    alpha = scal_ref[0, 0]   # lr / bias_correction1
    c2 = scal_ref[0, 1]      # 1 / sqrt(bias_correction2)
    lrwd = scal_ref[0, 2]    # lr * weight_decay (0 when decay masked off)
    seed_i = jax.lax.bitcast_convert_type(seed_ref[...], jnp.int32)[0, 0]
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m = beta1 * m_ref[...].astype(jnp.float32) + (1.0 - beta1) * g
    v = beta2 * v_ref[...].astype(jnp.float32) + (1.0 - beta2) * (g * g)
    denom = jnp.sqrt(v) * c2 + eps
    new_p = p - alpha * (m / denom) - lrwd * p
    # absolute element index (rows offset by the grid program)
    i = pl.program_id(0)
    br = om_ref.shape[0]
    rows = jax.lax.broadcasted_iota(jnp.int32, om_ref.shape, 0) \
        + i * np.int32(br)
    cc = jax.lax.broadcasted_iota(jnp.int32, om_ref.shape, 1)
    idx = rows * np.int32(cols) + cc + np.int32(bi)
    om_ref[...] = m.astype(om_ref.dtype)
    ov_ref[...] = v.astype(ov_ref.dtype)
    op_ref[...] = _sr_round_bf16(new_p, seed_i, idx)


def fused_adamw_sr_update(p, g, m, v, lr, step, seed_f, *, beta1=0.9,
                          beta2=0.999, eps=1e-8, weight_decay=0.0,
                          apply_decay=True):
    """Master-weight-free fused AdamW: bf16 params + bf16 moments, fp32 math
    in-VMEM, stochastic rounding on the param write. One pass reads
    g+p+m+v (8 B/param) and writes p+m+v (6 B/param) — ~36% less HBM
    traffic than the master-weight chain, and no fp32 master resident at
    all. Returns (new_p, new_m, new_v) or None when untileable."""
    shape = m.shape
    plan = _tile_plan(shape)
    if plan is None:
        return None
    rows, cols = plan
    stepf = step.astype(jnp.float32)
    bc1 = 1.0 - jnp.power(beta1, stepf)
    bc2 = 1.0 - jnp.power(beta2, stepf)
    lr32 = lr.astype(jnp.float32)
    wd = lr32 * weight_decay if (weight_decay and apply_decay) else \
        jnp.zeros((), jnp.float32)
    scalars = jnp.stack([lr32 / bc1, 1.0 / jnp.sqrt(bc2), wd]) \
        .astype(jnp.float32).reshape(1, 3)

    br = _pick_block(rows, cols)
    g2, p2 = g.reshape(rows, cols), p.reshape(rows, cols)
    m2, v2 = m.reshape(rows, cols), v.reshape(rows, cols)
    kernel = functools.partial(_adamw_sr_kernel, beta1=float(beta1),
                               beta2=float(beta2), eps=float(eps), bi=0,
                               cols=cols)
    bs = lambda: pl.BlockSpec((br, cols), lambda i: (i, Z))
    nm, nv, np_ = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((1, 3), lambda i: (Z, Z)),
                  pl.BlockSpec((1, 1), lambda i: (Z, Z)),
                  bs(), bs(), bs(), bs()],
        out_specs=(bs(), bs(), bs()),
        out_shape=(
            jax.ShapeDtypeStruct((rows, cols), m.dtype),
            jax.ShapeDtypeStruct((rows, cols), v.dtype),
            jax.ShapeDtypeStruct((rows, cols), p.dtype),
        ),
        input_output_aliases={4: 0, 5: 1, 3: 2},
        interpret=_interpret(),
    )(scalars, seed_f, g2, p2, m2, v2)
    return (np_.reshape(shape), nm.reshape(shape), nv.reshape(shape))


def fused_adamw_sr_update_sharded(mesh, spec, p, g, m, v, lr, step, seed_f,
                                  **kw):
    """Stochastic-rounding AdamW over SHARDED state (the ZeRO/TP composition
    of :func:`fused_adamw_sr_update`, mirroring
    :func:`fused_adamw_update_sharded`). Each device runs the SR kernel on
    its local shard; the rounding seed is folded with the device's mesh
    coordinates so shards draw decorrelated rounding streams. Returns
    (new_p, new_m, new_v) or None when the local shard isn't tileable."""
    local = _local_shape(mesh, spec, tuple(m.shape))
    if local is None or _tile_plan(local) is None:
        return None
    from jax.sharding import PartitionSpec
    ps = PartitionSpec(*(tuple(spec) + (None,) * (m.ndim - len(tuple(spec)))))
    rep = PartitionSpec()
    axes = [a for e in tuple(spec) if e is not None
            for a in (e if isinstance(e, tuple) else (e,))]

    def local_update(p_l, g_l, m_l, v_l, lr_s, step_s, seed_l):
        si = jax.lax.bitcast_convert_type(seed_l, jnp.int32)
        for ax in axes:
            si = si ^ (jax.lax.axis_index(ax).astype(jnp.int32)
                       * np.int32(-1640531527))
        seed_dev = jax.lax.bitcast_convert_type(si, jnp.float32)
        return fused_adamw_sr_update(p_l, g_l, m_l, v_l, lr_s, step_s,
                                     seed_dev, **kw)

    f = jax.shard_map(local_update, mesh=mesh,
                      in_specs=(ps, ps, ps, ps, rep, rep, rep),
                      out_specs=(ps, ps, ps), check_vma=False)
    return f(p, g, m, v, jnp.asarray(lr), jnp.asarray(step), seed_f)
