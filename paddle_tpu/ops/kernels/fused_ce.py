"""Memory-lean fused softmax cross-entropy (custom VJP).

Reference analog: phi's softmax_with_cross_entropy kernel, which never
materializes a separate fp32 log-probability tensor. The naive jax path costs
~3 extra full passes over the (tokens, vocab) logits in HBM: an fp32 upcast
copy, the saved fp32 softmax for backward, and the backward read of it — at
LLM vocab sizes (tokens x 32000) that is GBs of traffic per step.

This version keeps residuals to {bf16 logits (already live), fp32 lse (one
scalar per token), labels}: forward computes lse with fp32 accumulation
directly from the low-precision logits; backward reconstructs
softmax = exp(l - lse) on the fly and fuses the one-hot subtraction, so the
whole backward is ONE read + ONE write of the logits-sized buffer.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_softmax_ce(logits, labels, ignore_index=-100):
    """Per-token CE loss. logits (T, V) any float dtype; labels (T,) int.
    Returns fp32 loss (T,) with ignored positions zeroed."""
    loss, _ = _ce_fwd_impl(logits, labels, ignore_index)
    return loss


def _ce_fwd_impl(logits, labels, ignore_index):
    l32 = logits.astype(jnp.float32)  # XLA fuses the cast into the reductions
    m = jnp.max(l32, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(l32 - m[:, None]), axis=-1))
    idx = jnp.clip(labels.astype(jnp.int32), 0, logits.shape[-1] - 1)
    tgt = jnp.take_along_axis(l32, idx[:, None], axis=-1)[:, 0]
    valid = labels != ignore_index
    loss = jnp.where(valid, lse - tgt, 0.0)
    return loss, lse


def _ce_vjp_fwd(logits, labels, ignore_index):
    loss, lse = _ce_fwd_impl(logits, labels, ignore_index)
    return loss, (logits, labels, lse)


def _ce_vjp_bwd(ignore_index, res, g):
    logits, labels, lse = res
    idx = jnp.clip(labels.astype(jnp.int32), 0, logits.shape[-1] - 1)
    valid = (labels != ignore_index)
    scale = jnp.where(valid, g, 0.0).astype(jnp.float32)  # (T,)
    # softmax reconstructed from the saved bf16 logits + fp32 lse; the one-hot
    # subtraction folds into the same elementwise pass
    probs = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=jnp.float32)
    grad = (probs - onehot) * scale[:, None]
    return grad.astype(logits.dtype), None


fused_softmax_ce.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)
