"""Memory-lean fused softmax cross-entropy (custom VJP).

Reference analog: phi's softmax_with_cross_entropy kernel, which never
materializes a separate fp32 log-probability tensor. The naive jax path costs
~3 extra full passes over the (tokens, vocab) logits in HBM: an fp32 upcast
copy, the saved fp32 softmax for backward, and the backward read of it — at
LLM vocab sizes (tokens x 32000) that is GBs of traffic per step.

This version keeps residuals to {bf16 logits (already live), fp32 lse (one
scalar per token), labels}: forward computes lse with fp32 accumulation
directly from the low-precision logits; backward reconstructs
softmax = exp(l - lse) on the fly and fuses the one-hot subtraction, so the
whole backward is ONE read + ONE write of the logits-sized buffer.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_softmax_ce(logits, labels, ignore_index=-100):
    """Per-token CE loss. logits (T, V) any float dtype; labels (T,) int.
    Returns fp32 loss (T,) with ignored positions zeroed."""
    loss, _ = _ce_fwd_impl(logits, labels, ignore_index)
    return loss


def _ce_fwd_impl(logits, labels, ignore_index):
    l32 = logits.astype(jnp.float32)  # XLA fuses the cast into the reductions
    m = jnp.max(l32, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(l32 - m[:, None]), axis=-1))
    idx = jnp.clip(labels.astype(jnp.int32), 0, logits.shape[-1] - 1)
    tgt = jnp.take_along_axis(l32, idx[:, None], axis=-1)[:, 0]
    valid = labels != ignore_index
    loss = jnp.where(valid, lse - tgt, 0.0)
    return loss, lse


def _ce_vjp_fwd(logits, labels, ignore_index):
    loss, lse = _ce_fwd_impl(logits, labels, ignore_index)
    return loss, (logits, labels, lse)


def _ce_vjp_bwd(ignore_index, res, g):
    logits, labels, lse = res
    idx = jnp.clip(labels.astype(jnp.int32), 0, logits.shape[-1] - 1)
    valid = (labels != ignore_index)
    scale = jnp.where(valid, g, 0.0).astype(jnp.float32)  # (T,)
    # softmax reconstructed from the saved bf16 logits + fp32 lse; the one-hot
    # subtraction folds into the same elementwise pass
    probs = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=jnp.float32)
    grad = (probs - onehot) * scale[:, None]
    return grad.astype(logits.dtype), None


fused_softmax_ce.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_linear_ce(hidden, weight, bias, labels, ignore_index=-100,
                    chunk=8192):
    """Chunked fused (linear projection + softmax CE): per-token fp32 loss
    WITHOUT ever materializing the full (T, V) logits.

    The classifier head's logits (+ their grad) are the largest single
    activation of an MLM/LM step — bert-base at batch 96 x 512 is ~3 GB
    bf16 each way, the very tensor whose scheduling made the B=96 compile
    OOM nondeterministically. This computes loss and grads over row CHUNKS
    (lax.scan): forward keeps only {fp32 lse, target logit} per token;
    backward recomputes each chunk's logits (one extra T x H x V matmul
    pass, ~+6% step FLOPs for bert-base) and accumulates dW/db in fp32.

    hidden (T, H) bf16/f32; weight (H, V) paddle [in, out] layout; bias
    (V,) or None; labels (T,) int. Returns fp32 (T,) loss, ignored
    positions zeroed.
    """
    loss, _ = _flce_fwd_impl(hidden, weight, bias, labels, ignore_index,
                             chunk)
    return loss


def _flce_pad(hidden, labels, ignore_index, chunk):
    t = hidden.shape[0]
    n = -(-t // chunk)
    pad = n * chunk - t
    if pad:
        hidden = jnp.pad(hidden, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)
    return hidden, labels, n, t


def _flce_fwd_impl(hidden, weight, bias, labels, ignore_index, chunk):
    h_p, l_p, n, t = _flce_pad(hidden, labels, ignore_index, chunk)
    h_ch = h_p.reshape(n, chunk, h_p.shape[-1])
    l_ch = l_p.reshape(n, chunk)
    v = weight.shape[-1]

    def body(_, xs):
        h_c, lbl_c = xs
        logits = h_c @ weight
        if bias is not None:
            logits = logits + bias
        l32 = logits.astype(jnp.float32)
        m = jnp.max(l32, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(l32 - m[:, None]), axis=-1))
        idx = jnp.clip(lbl_c.astype(jnp.int32), 0, v - 1)
        tgt = jnp.take_along_axis(l32, idx[:, None], axis=-1)[:, 0]
        valid = lbl_c != ignore_index
        return None, (jnp.where(valid, lse - tgt, 0.0), lse)

    _, (loss, lse) = jax.lax.scan(body, None, (h_ch, l_ch))
    return loss.reshape(-1)[:t], lse.reshape(-1)[:t]


def _flce_vjp_fwd(hidden, weight, bias, labels, ignore_index, chunk):
    loss, lse = _flce_fwd_impl(hidden, weight, bias, labels, ignore_index,
                               chunk)
    return loss, (hidden, weight, bias, labels, lse)


def _flce_vjp_bwd(ignore_index, chunk, res, g):
    hidden, weight, bias, labels, lse = res
    v = weight.shape[-1]
    h_p, l_p, n, t = _flce_pad(hidden, labels, ignore_index, chunk)
    pad = n * chunk - t
    lse_p = jnp.pad(lse, (0, pad)) if pad else lse
    g_p = jnp.pad(g.astype(jnp.float32), (0, pad)) if pad \
        else g.astype(jnp.float32)
    h_ch = h_p.reshape(n, chunk, h_p.shape[-1])
    l_ch = l_p.reshape(n, chunk)
    lse_ch = lse_p.reshape(n, chunk)
    g_ch = g_p.reshape(n, chunk)

    def body(carry, xs):
        dW, db = carry
        h_c, lbl_c, lse_c, g_c = xs
        logits = h_c @ weight
        if bias is not None:
            logits = logits + bias
        probs = jnp.exp(logits.astype(jnp.float32) - lse_c[:, None])
        idx = jnp.clip(lbl_c.astype(jnp.int32), 0, v - 1)
        scale = jnp.where(lbl_c != ignore_index, g_c, 0.0)
        onehot = jax.nn.one_hot(idx, v, dtype=jnp.float32)
        dl = (probs - onehot) * scale[:, None]
        dl16 = dl.astype(h_c.dtype)
        dh_c = dl16 @ weight.T
        dW = dW + jax.lax.dot_general(
            h_c, dl16, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        db = db + jnp.sum(dl, axis=0)
        return (dW, db), dh_c

    dW0 = jnp.zeros(weight.shape, jnp.float32)
    db0 = jnp.zeros((v,), jnp.float32)
    (dW, db), dh = jax.lax.scan(body, (dW0, db0),
                                (h_ch, l_ch, lse_ch, g_ch))
    dh = dh.reshape(-1, hidden.shape[-1])[:t]
    dbias = db.astype(bias.dtype) if bias is not None else None
    return (dh.astype(hidden.dtype), dW.astype(weight.dtype), dbias, None)


fused_linear_ce.defvjp(_flce_vjp_fwd, _flce_vjp_bwd)
