"""Mixture-of-Experts core: static-shape gating, dispatch/combine, EP all_to_all.

Reference: incubate/distributed/models/moe/moe_layer.py:261 (MoELayer with
global_scatter/global_gather alltoall ops) and gate/{gshard,switch,naive}_gate.py.

TPU-native redesign: instead of the reference's ragged scatter/gather CUDA ops,
tokens are routed with the GShard capacity algorithm at STATIC shapes — dispatch
and combine are [T, E, C] einsum masks, so the whole layer is dense matmuls the
MXU tiles well, and expert parallelism is one `lax.all_to_all` over the `ep`
mesh axis inside shard_map. Everything here operates on raw jax arrays.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def top_k_gating(logits, top_k, capacity, *, jitter_key=None, jitter_eps=0.0,
                 norm_topk=True):
    """GShard/Switch gating at static shapes.

    logits: [T, E] router scores. Returns (dispatch [T,E,C] bool,
    combine [T,E,C] float, aux_loss scalar, router_probs [T,E]).

    top_k=1 → Switch; top_k=2 → GShard top-2 with renormalized weights.
    Tokens overflowing an expert's capacity C are dropped (contribute 0),
    matching the reference's capacity semantics.
    """
    t, e = logits.shape
    if jitter_key is not None and jitter_eps > 0.0:
        noise = jax.random.uniform(jitter_key, logits.shape,
                                   minval=1.0 - jitter_eps, maxval=1.0 + jitter_eps)
        logits = logits * noise
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((t, e, capacity), bool)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    masks = []
    gates = []
    p = probs
    for k in range(top_k):
        idx = jnp.argmax(p, axis=-1)                     # [T]
        m = jax.nn.one_hot(idx, e, dtype=jnp.float32)    # [T,E]
        gates.append(jnp.sum(probs * m, axis=-1))        # gate prob of choice k
        masks.append(m)
        p = p * (1.0 - m)                                # exclude chosen expert

    # positions within each expert's buffer, counting all k-levels in order
    # (k=0 choices fill first, like the reference's prioritized dispatch)
    prev_counts = jnp.zeros((e,), jnp.float32)
    positions = []
    for m in masks:
        pos = jnp.cumsum(m, axis=0) - m + prev_counts[None, :]   # [T,E]
        positions.append(jnp.sum(pos * m, axis=-1))              # [T]
        prev_counts = prev_counts + jnp.sum(m, axis=0)

    # normalize top-k gate weights over the kept experts
    denom = sum(gates) if (top_k > 1 and norm_topk) else None
    for k, (m, g, pos) in enumerate(zip(masks, gates, positions)):
        keep = (pos < capacity) & (jnp.sum(m, axis=-1) > 0)
        w = g / jnp.maximum(denom, 1e-9) if denom is not None else g
        pos_c = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
        oh_pos = jax.nn.one_hot(pos_c, capacity, dtype=jnp.float32)  # [T,C]
        contrib = m[:, :, None] * oh_pos[:, None, :]                  # [T,E,C]
        contrib = contrib * keep[:, None, None]
        dispatch = dispatch | (contrib > 0)
        combine = combine + contrib * w[:, None, None]

    # GShard load-balancing loss: E * mean_e(frac_tokens_e * mean_prob_e)
    me = jnp.mean(probs, axis=0)                          # [E]
    ce = jnp.mean(masks[0], axis=0)                       # fraction routed (k=0)
    aux_loss = e * jnp.sum(me * ce)
    return dispatch, combine, aux_loss, probs


def moe_ffn(dispatched, w_gate, w_up, w_down, activation="swiglu"):
    """Stacked-expert FFN: dispatched [E, C, D] -> [E, C, D].

    w_gate/w_up: [E, D, F]; w_down: [E, F, D]. swiglu (llama-style) or gelu
    (w_gate unused for gelu).
    """
    if activation == "swiglu":
        g = jnp.einsum("ecd,edf->ecf", dispatched, w_gate)
        u = jnp.einsum("ecd,edf->ecf", dispatched, w_up)
        h = jax.nn.silu(g) * u
    else:
        u = jnp.einsum("ecd,edf->ecf", dispatched, w_up)
        h = jax.nn.gelu(u)
    return jnp.einsum("ecf,efd->ecd", h, w_down)


def moe_forward_dense(x, router_w, w_gate, w_up, w_down, *, top_k=2,
                      capacity_factor=2.0, activation="swiglu"):
    """Single-device MoE on [T, D] tokens; returns (y [T,D], aux_loss)."""
    t, d = x.shape
    e = router_w.shape[1]
    capacity = max(int(capacity_factor * t / e), top_k)
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    dispatch, combine, aux, _ = top_k_gating(logits, top_k, capacity)
    dispatched = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    h = moe_ffn(dispatched, w_gate, w_up, w_down, activation)
    y = jnp.einsum("tec,ecd->td", combine.astype(h.dtype), h)
    return y, aux


def moe_forward_ep(x, router_w, w_gate, w_up, w_down, axis_name, *, top_k=2,
                   capacity_factor=2.0, activation="swiglu"):
    """Expert-parallel MoE inside shard_map.

    x: [T_local, D] local token shard; w_*: [E_local, ...] local expert shard
    (E = E_local * ep_size). Dispatch goes through one all_to_all each way:
    [E, C, D] -> (exchange) -> [E_local, ep*C, D] so each rank runs only its
    experts over every rank's tokens (reference: global_scatter/global_gather).
    """
    n = jax.lax.psum(1, axis_name)
    t = x.shape[0]
    e_local = w_up.shape[0]
    e = e_local * n
    capacity = max(int(capacity_factor * t / e), top_k)
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        router_w.astype(jnp.float32))
    dispatch, combine, aux, _ = top_k_gating(logits, top_k, capacity)
    dispatched = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    # [E, C, D] -> expert-block j to rank j, buffers concat along capacity:
    # rank j ends up with [E_local, N*C, D] (its experts, every rank's tokens)
    recv = jax.lax.all_to_all(dispatched, axis_name, split_axis=0,
                              concat_axis=1, tiled=True)
    h = moe_ffn(recv, w_gate, w_up, w_down, activation)
    # reverse: capacity chunk r back to token-owner r, expert blocks re-stack
    h_home = jax.lax.all_to_all(h, axis_name, split_axis=1, concat_axis=0,
                                tiled=True)
    y = jnp.einsum("tec,ecd->td", combine.astype(h_home.dtype), h_home)
    # aux loss averaged over ranks (each rank computed it on its local tokens)
    aux = jax.lax.pmean(aux, axis_name)
    return y, aux
