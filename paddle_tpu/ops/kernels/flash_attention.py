"""Pallas TPU flash attention (forward + backward).

Reference analog: the FA2 CUDA library behind phi/kernels/gpu/flash_attn_kernel.cu
and python/paddle/nn/functional/flash_attention.py. This is a from-scratch TPU
kernel: online-softmax tiles sized for the MXU (q blocks x kv blocks, fp32
accumulators in VMEM), causal block skipping via dynamic loop bounds, GQA handled
zero-copy by mapping q-head grid indices onto kv heads in the BlockSpec index_map.

Layout contract: public API takes paddle's [B, S, H, D]; kernels run [B*H, S, D].
On non-TPU backends the same kernels run under interpret mode (tests), so CPU and
TPU execute identical code.
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os

DEFAULT_BLOCK_Q = int(os.environ.get("PT_FLASH_BLOCK_Q", "256"))
DEFAULT_BLOCK_K = int(os.environ.get("PT_FLASH_BLOCK_K", "512"))
# the backward kernels prefer a larger q block than the forward (they loop
# q-blocks innermost for dk/dv). Measured in-process, n=100 reps (B=3 S=2048
# H=32 D=128, v5e): fwd(256,512)+bwd(512,512) = 5.24 ms vs 6.02 ms with
# shared (256,512) — ~69 TF/s combined.
# bwd defaults are independent of the fwd env overrides: tuning the fwd
# q-block (e.g. down to 128 for VMEM) must not silently drop the measured
# 512 bwd default — set PT_FLASH_BLOCK_*_BWD explicitly to change these
DEFAULT_BLOCK_Q_BWD = int(os.environ.get("PT_FLASH_BLOCK_Q_BWD", "512"))
DEFAULT_BLOCK_K_BWD = int(os.environ.get("PT_FLASH_BLOCK_K_BWD", "512"))
NEG_INF = np.float32(-1e30)
# Index-map literals MUST be i32: python ints become i64 constants under the
# framework's jax_enable_x64 and Mosaic then fails to legalize the index-map
# functions ("failed to legalize operation 'func.return'").
Z = np.int32(0)


def _interpret():
    return jax.default_backend() not in ("tpu",)


def _keep_mask(seed_i, bh_i, rows, cols, sq, sk, dropout_p):
    """Deterministic per-ELEMENT dropout mask from the absolute (head, row,
    col) position — a murmur3-style integer hash, so forward and backward
    reproduce the identical mask even with DIFFERENT block tilings (the
    bwd kernels use larger q blocks). int32 arithmetic wraps (two's
    complement) — the few collisions from wraparound are irrelevant for
    dropout. Uses 31 uniform bits via an unsigned-free compare."""
    idx = (bh_i * np.int32(sq) + rows) * np.int32(sk) + cols
    h = idx * np.int32(-1640531527) + seed_i          # 0x9E3779B9
    h = h ^ jax.lax.shift_right_logical(h, np.int32(16))
    h = h * np.int32(-2048144789)                     # 0x85EBCA6B
    h = h ^ jax.lax.shift_right_logical(h, np.int32(13))
    h = h * np.int32(-1028477387)                     # 0xC2B2AE35
    h = h ^ jax.lax.shift_right_logical(h, np.int32(16))
    hb = h & np.int32(0x7FFFFFFF)
    thr = np.int32(int(dropout_p * 2147483648.0))
    return hb >= thr


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------



def _kv_index_map(group):
    """Map q-head grid index -> kv-head row (GQA). lax.div keeps i32 under x64
    (a plain `//` promotes and breaks Mosaic's index-map lowering)."""
    if group == 1:
        return lambda i, j: (i, Z, Z)
    return lambda i, j: (jax.lax.div(i, np.int32(group)), Z, Z)


def _kv_block_index_map(group):
    if group == 1:
        return lambda i, j: (i, j, Z)
    return lambda i, j: (jax.lax.div(i, np.int32(group)), j, Z)


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                causal, bq, bk, sq, sk, dropout_p):
    bq_i, bk_i = np.int32(bq), np.int32(bk)  # i32 scalars for index math (x64 on)
    q = q_ref[0].astype(jnp.float32) * np.float32(scale)   # [bq, D]
    bh_i = pl.program_id(0)
    jq = pl.program_id(1)
    num_kv = sk // bk
    seed_i = jax.lax.bitcast_convert_type(seed_ref[...],
                                          jnp.int32)[0, 0]

    if causal:
        # last kv block that intersects rows [jq*bq, jq*bq+bq)
        limit = jnp.minimum((jq * bq_i + bq_i + bk_i - np.int32(1)) // bk_i,
                            np.int32(num_kv)).astype(jnp.int32)
    else:
        limit = jnp.int32(num_kv)

    def body(kv_i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kv_i * bk_i, bk), :]        # [bk, D]
        v = v_ref[0, pl.ds(kv_i * bk_i, bk), :]
        s = jax.lax.dot_general(q, k.astype(jnp.float32),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        rows = jq * bq_i + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kv_i * bk_i + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # the normalizer uses the UNmasked p: dropout applies to the
        # normalized probabilities (reference softmax-then-dropout), and the
        # lse must stay a dropout-free statistic for the backward
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_i, bh_i, rows, cols, sq, sk, dropout_p)
            p = jnp.where(keep, p, 0.0) * np.float32(1.0 / (1.0 - dropout_p))
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    D = q_ref.shape[-1]
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(jnp.int32(0), limit, body, (m0, l0, a0))
    l = jnp.maximum(l, np.float32(1e-30))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)            # [bq, 1]


def _fwd(q, k, v, causal, scale, bq, bk, dropout_p, seed_f):
    """q: [BHq, Sq, D]; k/v: [BHkv, Sk, D]."""
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    group = bh // bh_kv
    grid = (bh, sq // bq)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq,
                               bk=bk, sq=sq, sk=sk, dropout_p=dropout_p)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (Z, Z)),
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, Z)),
            pl.BlockSpec((1, sk, d), _kv_index_map(group)),
            pl.BlockSpec((1, sk, d), _kv_index_map(group)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, Z)),
            pl.BlockSpec((1, bq, 1), lambda i, j: (i, j, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(seed_f, q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, bq, bk, sq, sk,
                    dropout_p):
    bq_i, bk_i = np.int32(bq), np.int32(bk)
    scale = np.float32(scale)
    k = k_ref[0].astype(jnp.float32)                  # [bk, D]
    v = v_ref[0].astype(jnp.float32)
    bh_i = pl.program_id(0)
    jk = pl.program_id(1)
    num_q = sq // bq
    start = ((jk * bk_i) // bq_i).astype(jnp.int32) if causal else jnp.int32(0)
    seed_i = jax.lax.bitcast_convert_type(seed_ref[...],
                                          jnp.int32)[0, 0]
    inv_keep = np.float32(1.0 / (1.0 - dropout_p)) if dropout_p > 0.0 else None

    D = k_ref.shape[-1]

    def body(q_i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(q_i * bq_i, bq), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(q_i * bq_i, bq), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(q_i * bq_i, bq), :]                         # [bq,1]
        delta = delta_ref[0, pl.ds(q_i * bq_i, bq), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)       # [bq,bk]
        rows = q_i * bq_i + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = jk * bk_i + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                                              # [bq,bk]
        # with dropout, the weights actually used were z = keep*p/keep_prob
        # (same position-hashed mask as the forward); d/dp gets the same
        # mask: softmax-bwd delta is unchanged (delta = sum(do*o))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_i, bh_i, rows, cols, sq, sk, dropout_p)
            z = jnp.where(keep, p, 0.0) * inv_keep
            dp = jnp.where(keep, dp, 0.0) * inv_keep
        else:
            z = p
        dv = dv + jax.lax.dot_general(z, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                             # [bq,bk]
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bk, D), jnp.float32)
    dv0 = jnp.zeros((bk, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, jnp.int32(num_q), body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)   # note: dk already includes `scale` via q
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, scale, causal, bq, bk, sq, sk, dropout_p):
    bq_i, bk_i = np.int32(bq), np.int32(bk)
    scale = np.float32(scale)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]          # [bq, 1]
    delta = delta_ref[0]
    bh_i = pl.program_id(0)
    jq = pl.program_id(1)
    num_kv = sk // bk
    limit = (jnp.minimum((jq * bq_i + bq_i + bk_i - np.int32(1)) // bk_i,
                         np.int32(num_kv)).astype(jnp.int32)
             if causal else jnp.int32(num_kv))
    seed_i = jax.lax.bitcast_convert_type(seed_ref[...],
                                          jnp.int32)[0, 0]
    inv_keep = np.float32(1.0 / (1.0 - dropout_p)) if dropout_p > 0.0 else None
    D = q_ref.shape[-1]

    def body(kv_i, dq):
        k = k_ref[0, pl.ds(kv_i * bk_i, bk), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kv_i * bk_i, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        rows = jq * bq_i + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kv_i * bk_i + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_i, bh_i, rows, cols, sq, sk, dropout_p)
            dp = jnp.where(keep, dp, 0.0) * inv_keep
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(jnp.int32(0), limit, body,
                           jnp.zeros((bq, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, scale, bq, bk, dropout_p, seed_f):
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    group = bh // bh_kv
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
                    keepdims=True)  # [BH, Sq, 1]

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                                   bq=bq, bk=bk, sq=sq, sk=sk,
                                   dropout_p=dropout_p)
    # dk/dv computed per Q-head then summed over the GQA group
    dk_h, dv_h = pl.pallas_call(
        dkv_kernel,
        grid=(bh, sk // bk),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (Z, Z)),
            pl.BlockSpec((1, sq, d), lambda i, j: (i, Z, Z)),
            pl.BlockSpec((1, bk, d), _kv_block_index_map(group)),
            pl.BlockSpec((1, bk, d), _kv_block_index_map(group)),
            pl.BlockSpec((1, sq, d), lambda i, j: (i, Z, Z)),
            pl.BlockSpec((1, sq, 1), lambda i, j: (i, Z, Z)),
            pl.BlockSpec((1, sq, 1), lambda i, j: (i, Z, Z)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, Z)),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(seed_f, q, k, v, do, lse, delta)
    if group > 1:
        dk = dk_h.reshape(bh_kv, group, sk, d).sum(axis=1).astype(k.dtype)
        dv = dv_h.reshape(bh_kv, group, sk, d).sum(axis=1).astype(v.dtype)
    else:
        dk, dv = dk_h.astype(k.dtype), dv_h.astype(v.dtype)

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                  bq=bq, bk=bk, sq=sq, sk=sk,
                                  dropout_p=dropout_p)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, sq // bq),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (Z, Z)),
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, Z)),
            pl.BlockSpec((1, sk, d), _kv_index_map(group)),
            pl.BlockSpec((1, sk, d), _kv_index_map(group)),
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, Z)),
            pl.BlockSpec((1, bq, 1), lambda i, j: (i, j, Z)),
            pl.BlockSpec((1, bq, 1), lambda i, j: (i, j, Z)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, Z)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=_interpret(),
    )(seed_f, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API with custom VJP — [B, S, H, D] layout
# ---------------------------------------------------------------------------

def _to_bhsd(x):
    b, s, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, s, d), (b, h)


def _from_bhsd(x, bh_shape):
    b, h = bh_shape
    bhd, s, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, s, d), 1, 2)


def _pick_blocks(s, default):
    blk = min(default, s)
    while s % blk != 0:
        blk //= 2
    return max(blk, 1)


def _zero_seed():
    # host constant, NEVER a cached jnp array: the first call can happen
    # inside a trace (remat/jit) and a cached tracer would leak out of it
    return np.zeros((1, 1), np.float32)


def seed_carrier(key):
    """Fold a jax PRNG key into the (1,1) f32 bit-carrier the kernels take
    (f32 so it can pass through custom_vjp with a plain zero cotangent;
    kernels bitcast it back to int32 for the position-hashed dropout)."""
    bits = jax.random.bits(key, (1, 1), jnp.uint32)
    return jax.lax.bitcast_convert_type(bits.astype(jnp.uint32),
                                        jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_core(q, k, v, causal, scale, dropout_p, seed_f):
    out, _ = _flash_fwd_res(q, k, v, causal, scale, dropout_p, seed_f)
    return out


def flash_attention_fwd(q, k, v, causal=False, scale=None, dropout_p=0.0,
                        seed_f=None):
    """Flash attention with optional in-kernel dropout. ``seed_f``: the
    (1,1) f32 bit-carrier from :func:`seed_carrier` (required when
    dropout_p > 0 and training randomness should vary per step)."""
    if seed_f is None:
        seed_f = _zero_seed()
    return _flash_core(q, k, v, causal, scale, float(dropout_p), seed_f)


def _flash_fwd_res(q, k, v, causal, scale, dropout_p=0.0, seed_f=None):
    # kernel masks top-left aligned; bottom-right (paddle) semantics only
    # coincide for equal lengths — hard error beats silent corruption.
    assert not causal or q.shape[1] == k.shape[1], \
        "flash_attention_fwd: causal requires seq_q == seq_k (decode goes " \
        "through scaled_dot_product_attention's XLA path)"
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if seed_f is None:
        seed_f = _zero_seed()
    q3, bhq = _to_bhsd(q)
    k3, _ = _to_bhsd(k)
    v3, _ = _to_bhsd(v)
    bq = _pick_blocks(q3.shape[1], DEFAULT_BLOCK_Q)
    bk = _pick_blocks(k3.shape[1], DEFAULT_BLOCK_K)
    o3, lse = _fwd(q3, k3, v3, causal, scale, bq, bk, dropout_p, seed_f)
    out = _from_bhsd(o3, bhq)
    return out, (q3, k3, v3, o3, lse, bhq, scale, seed_f)


def _flash_vjp_fwd(q, k, v, causal, scale, dropout_p, seed_f):
    out, res = _flash_fwd_res(q, k, v, causal, scale, dropout_p, seed_f)
    return out, res


def _flash_vjp_bwd(causal, scale_arg, dropout_p, res, g):
    q3, k3, v3, o3, lse, bhq, scale, seed_f = res
    b, h = bhq
    do3, _ = _to_bhsd(g)
    bq_b = _pick_blocks(q3.shape[1], DEFAULT_BLOCK_Q_BWD)
    bk_b = _pick_blocks(k3.shape[1], DEFAULT_BLOCK_K_BWD)
    dq3, dk3, dv3 = _bwd(q3, k3, v3, o3, lse, do3, causal, scale, bq_b, bk_b,
                         dropout_p, seed_f)
    kv_h = k3.shape[0] // b
    dq = _from_bhsd(dq3, (b, h))
    dk = _from_bhsd(dk3, (b, kv_h))
    dv = _from_bhsd(dv3, (b, kv_h))
    return dq, dk, dv, jnp.zeros_like(seed_f)


_flash_core.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)
