"""Pallas TPU fused int4-dequant matmul — weight-only int4 decode GEMM.

Reference analog: the reference's weight-only quantized GEMMs
(paddle/phi/kernels/fusion/cutlass/ weight-only int4/int8 paths behind
nn/quant/quantized_linear.py weight_only_linear). On TPU the XLA lowering of
"unpack nibbles, then matmul" MATERIALIZES the two unpacked int8 planes in
HBM every call — the unpack traffic erases int4's bandwidth win (measured:
int4 split-nibble 7.7k decode tok/s vs int8 10.4k at the 879M config).

This kernel streams the PACKED bytes (half of int8's weight traffic) and
extracts nibbles in registers:

  * packed int8 tile [kt2, ot] -> int32 -> low = (p<<28)>>28 (sign-extended
    low nibble), high = p>>4 (arithmetic shift; byte sign = high-nibble sign)
  * the activation row-pairing is handled OUTSIDE the kernel: x splits once
    into even/odd columns (x is tiny next to W), so the kernel is two plain
    MXU dots per tile: acc += xe @ low + xo @ high
  * per-output scales apply on the final k tile.

Falls back to the split-nibble jax path off-TPU or for non-tileable shapes
(callers guard; see nn/quant weight_only_linear).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Z = np.int32(0)

# measured on v5e at the llama ff shape (4096x11264, 8 rows): (512, 512)
# runs 0.52 ms/mm vs int8's 0.73 and the split-nibble XLA path's 0.94 —
# both XLA baselines stream ~107 GB/s effective here, so halving the weight
# bytes halves the time once the unpack stays in registers
_KT2 = 512   # packed-k tile (int8 sublane multiple)
_OT = 512    # out tile (lane multiple)


def _int4_mm_kernel(xe_ref, xo_ref, p_ref, s_ref, o_ref, acc_ref, *, nk):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    p = p_ref[...].astype(jnp.int32)                 # [kt2, ot]
    low = jnp.right_shift(jnp.left_shift(p, 28), 28)
    high = jnp.right_shift(p, 4)
    xe = xe_ref[...]                                 # [B, kt2]
    xo = xo_ref[...]
    dot = functools.partial(jax.lax.dot_general,
                            dimension_numbers=(((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    acc_ref[...] += dot(xe, low.astype(xe.dtype)) + \
        dot(xo, high.astype(xo.dtype))

    @pl.when(k == nk - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] *
                      s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def int4_matmul_tileable(n_in, n_out):
    """Shapes this kernel serves without padding weights."""
    return n_in % (2 * _KT2) == 0 and n_out % _OT == 0


def int4_matmul(x, packed, scales, out_dtype=None):
    """x [rows, n_in] @ dequant(packed [n_in/2, n_out] int4-pairs) * scales.

    Nibble convention matches weight_quantize: packed row r = original rows
    2r (low nibble) and 2r+1 (high). Requires int4_matmul_tileable shapes;
    rows pad to the MXU's 8-row granule internally.
    """
    rows, n_in = x.shape
    kt2_rows, n_out = packed.shape
    # rows bound = the VMEM budget: whole (rows_p, _KT2) x-blocks and a
    # (rows_p, _OT) fp32 accumulator stay resident per grid step
    assert n_in == 2 * kt2_rows and int4_matmul_tileable(n_in, n_out) \
        and rows <= 128, (rows, n_in, n_out)
    if out_dtype is None:
        out_dtype = x.dtype

    rows_p = max(8, -(-rows // 8) * 8)
    if rows_p != rows:
        x = jnp.pad(x, ((0, rows_p - rows), (0, 0)))
    xe = x[:, 0::2]                                  # pairs with low nibble
    xo = x[:, 1::2]
    nk = kt2_rows // _KT2
    no = n_out // _OT

    out = pl.pallas_call(
        functools.partial(_int4_mm_kernel, nk=nk),
        grid=(no, nk),
        in_specs=[
            pl.BlockSpec((rows_p, _KT2), lambda o, k: (Z, k)),
            pl.BlockSpec((rows_p, _KT2), lambda o, k: (Z, k)),
            pl.BlockSpec((_KT2, _OT), lambda o, k: (k, o)),
            pl.BlockSpec((1, _OT), lambda o, k: (Z, o)),
        ],
        out_specs=pl.BlockSpec((rows_p, _OT), lambda o, k: (Z, o)),
        out_shape=jax.ShapeDtypeStruct((rows_p, n_out), out_dtype),
        scratch_shapes=[pltpu.VMEM((rows_p, _OT), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=jax.default_backend() not in ("tpu",),
    )(xe, xo, packed, scales.reshape(1, -1))
    return out[:rows]
