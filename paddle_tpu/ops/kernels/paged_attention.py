"""Pallas TPU paged-attention decode kernel (block-sparse KV reads + GQA).

Reference analog: the phi block_multi_head_attention CUDA kernel behind
python/paddle/incubate/nn/functional/block_multihead_attention.py — the
vLLM-style paged attention the serving path decodes through. The XLA
fallback in incubate (gather every sequence's whole KV out of the pools,
dense einsum over the padded horizon) moves O(B * max_blocks * block_size)
HBM bytes per decode step regardless of live lengths; this kernel reads KV
**directly from the physical block pools**, touching only each sequence's
live blocks.

Design (mirrors ops/kernels/flash_attention.py idiom, adapted to paging):

- grid = (batch, kv_head, max_blocks); ``block_tables`` [B, MB] and
  ``seq_lens`` [B] ride in as **scalar-prefetched** SMEM operands
  (``PrefetchScalarGridSpec``), so the K/V BlockSpec index maps translate
  the logical block id of each grid step into the physical pool block to
  DMA — the pools never materialize a gathered [B, MB, H, bs, D] copy.
- block-sparse reads: grid steps past a sequence's last live block clamp
  their index map to the last live block's physical index. Pallas only
  issues a copy when the mapped block CHANGES between steps, so the dead
  tail costs zero HBM traffic; its compute is skipped with ``pl.when``.
- online softmax across the block loop: fp32 (m, l, acc) VMEM scratch
  carried over the innermost grid dimension, initialized at block 0,
  finalized (acc / l) into the output at the last block step.
- GQA zero-copy: q arrives [B, Hkv, G, D] (G = q-heads per kv head); each
  (batch, kv_head) window attends its whole q-head group against one
  stream of that kv head's blocks.
- optional fused new-token write: the decode step's fresh K/V (one token
  per sequence) is merged into the last live block IN VMEM — attention
  sees the new token without a prior XLA scatter round-trip through HBM —
  and the merged block is written back to the pools via
  ``input_output_aliases`` (in-place, one [bs, D] block write per
  (batch, kv_head)).

Invalid (-1) table entries: reads clamp to physical block 0 and are either
compute-skipped (dead tail) or masked by ``seq_lens``; fused writes route
to the pool's LAST physical block. Callers whose live write target can be
-1 (the serving engine: freed slots keep stale lens with wiped tables)
must reserve one trailing scratch block in the pool — see
``LLMEngine``'s ``+1`` pool allocation. Callers that guarantee valid
tables everywhere (``generate()``'s arange tables) need no spare block:
the clamp never fires.

On non-TPU backends the same kernel runs under interpret mode (parity
tests); the production CPU path stays the XLA dense-gather fallback in
``incubate.nn.functional.block_multihead_attention`` (see
``paged_attention_enabled``).

``paged_attention_append`` extends the decode kernel from q_len=1 to
q_len=chunk **append attention** — the mixed prefill+decode step of the
fused token-budget scheduler (``LLMEngine(scheduler="fused")``): each
sequence appends ``q_lens[b]`` new positions at ``seq_lens[b]``, every
query row attends causally to its own chunk prefix plus all prior pooled
KV, and the whole chunk's K/V writes back to the pools in-kernel (the
write can span several blocks; each overlapped block is merged in VMEM
and stored through the aliased pool outputs). Same gating: TPU fast path
behind ``FLAGS_use_paged_attention``, dense append fallback on CPU.

**Quantized KV pools** (``quant="int8"|"int4"``, the serving engine's
``kv_cache_dtype``): the physical pools store int8 (or int4
nibble-packed on D — see :func:`kv_unpack` for the split-half layout)
with one fp32 scale per (physical block, kv head) riding in
``k_scale``/``v_scale`` [num_blocks, Hkv] arrays. Both kernels
dequantize each block IN VMEM during the online-softmax walk
(``int * scale`` right after the block DMA — HBM traffic shrinks by
2x/4x, the f32 attention math is unchanged), and the fused write
re-quantizes IN VMEM too: the written block is merged in f32, its new
per-head absmax scale computed in-kernel, and the int payload + scale
store back through aliased outputs — no bf16 block ever round-trips to
HBM. Scale granularity is deliberately per-(block, head): one f32 per
``block_size * head_dim`` ints (<0.1% overhead), coarse enough to ride
the scalar path, fine enough that one outlier head can't flatten the
whole pool.
"""
from __future__ import annotations

import contextlib
import functools
import math
import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = np.float32(-1e30)
# index-map literals MUST be i32: python ints become i64 constants under the
# framework's jax_enable_x64 and Mosaic then fails to legalize the index maps
Z = np.int32(0)


#: symmetric integer grid per KV quantization mode. int4 uses [-7, 7]
#: (not -8) so the grid is symmetric and the absmax scale is exact at
#: both ends; the nibble stores the value offset by +8 (range [1, 15]).
KV_QMAX = {"int8": 127.0, "int4": 7.0}


def kv_packed_dim(D, quant):
    """Last (head) dim of the quantized pool storage: D int8 bytes for
    int8, ceil(D/2) bytes for int4 (two nibbles per byte; odd D pads one
    nibble — see :func:`kv_unpack`)."""
    if quant is None:
        return D
    if quant == "int8":
        return D
    if quant == "int4":
        return (D + 1) // 2
    raise ValueError(f"unknown kv quant dtype {quant!r}")


def kv_unpack(vals, quant, D):
    """Quantized storage -> UNSCALED f32 integer grid values, last dim
    packed->D. int4 uses a SPLIT-HALF layout (Mosaic-friendly: no
    per-element interleave): byte j of a row holds element ``j`` in its
    low nibble and element ``Dp + j`` (Dp = ceil(D/2)) in its high
    nibble, each stored offset-8 (q + 8 in [1, 15]); odd D leaves the
    final high nibble as padding, sliced off here."""
    if quant == "int8":
        return vals.astype(jnp.float32)
    b = vals.astype(jnp.int32) & 0xFF
    lo = (b & 0xF) - 8
    hi = ((b >> 4) & 0xF) - 8
    return jnp.concatenate([lo, hi], axis=-1)[..., :D] \
        .astype(jnp.float32)


def kv_pack(q, quant):
    """Integer grid values (f32/int, already clipped to the symmetric
    grid) -> int8 storage, packing nibble pairs for int4 in the
    split-half layout of :func:`kv_unpack`."""
    q = q.astype(jnp.int32)
    if quant == "int8":
        return q.astype(jnp.int8)
    D = q.shape[-1]
    Dp = (D + 1) // 2
    if 2 * Dp != D:
        pad = jnp.zeros(q.shape[:-1] + (1,), q.dtype)
        q = jnp.concatenate([q, pad], axis=-1)
    lo = q[..., :Dp] + 8
    hi = q[..., Dp:] + 8
    return (lo | (hi << 4)).astype(jnp.int8)


def kv_quantize(x, scale, quant):
    """f32 values + (broadcastable) per-block scale -> packed storage:
    round-to-nearest-even onto the symmetric grid, clip, pack."""
    qmax = np.float32(KV_QMAX[quant])
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, np.float32(1e-20))),
                 -qmax, qmax)
    return kv_pack(q, quant)


def kv_block_scale(x, quant, axes):
    """Absmax scale of one (or a batch of) f32 block(s) over ``axes``:
    THE one copy of the scale rule — the Pallas fused writes, the XLA
    dense fallback, and the engine's prefill scatter all compute the
    block scale through here, so kernel-vs-fallback parity holds to
    rounding."""
    return jnp.max(jnp.abs(x), axis=axes) / np.float32(KV_QMAX[quant])


def _interpret():
    return jax.default_backend() not in ("tpu",)


def paged_attention_enabled():
    """True when ``block_multihead_attention`` routes decode through this
    kernel: the ``use_paged_attention`` flag (env: FLAGS_use_paged_attention)
    is on AND the backend is a real TPU. Tier-1 CI runs under
    JAX_PLATFORMS=cpu, so CPU always takes the dense-gather fallback —
    deterministic and kernel-free (tests/conftest.py asserts this); the
    kernel itself is still exercised on CPU by the interpret-mode parity
    suite calling :func:`paged_attention_decode` directly."""
    from ...core.flags import flag_value
    return bool(flag_value("use_paged_attention")) and not _interpret()


# ---------------------------------------------------------------------------
# tensor-parallel routing (the multichip serving subsystem)
# ---------------------------------------------------------------------------

#: trace-time TP context: (mesh, axis) while an LLMEngine with a tp mesh is
#: tracing its paged step programs, else None. A pallas_call cannot be
#: auto-partitioned by GSPMD, so the sharded engine must route through the
#: explicit shard_map wrappers below — the engine arms this context around
#: its (trace-triggering) paged dispatches and block_multihead_attention's
#: kernel branch consults it. THREAD-LOCAL: N replica servers (one engine
#: thread each, possibly different meshes/models) may trace concurrently,
#: and replica A's trace must never read replica B's mesh.
_TP_CTX = threading.local()


@contextlib.contextmanager
def paged_tp_context(mesh, axis="tp"):
    """Arm the kernel TP routing for the duration of a (possibly
    trace-triggering) dispatch. Trace-time state, not run-time: once the
    program is compiled the context is a no-op thread-local set/reset."""
    prev = getattr(_TP_CTX, "value", None)
    _TP_CTX.value = (mesh, axis)
    try:
        yield
    finally:
        _TP_CTX.value = prev


def current_paged_tp():
    """The armed (mesh, axis) TP context of THIS thread, or None."""
    return getattr(_TP_CTX, "value", None)


def _tp_shard_map(fn, mesh, axis, in_specs, out_specs):
    from ...core.jax_compat import shard_map
    if isinstance(in_specs, list):
        in_specs = tuple(in_specs)
    if isinstance(out_specs, list):
        out_specs = tuple(out_specs)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)


def paged_attention_decode_tp(q, k_pool, v_pool, block_tables, seq_lens,
                              mesh, axis="tp", scale=None, new_k=None,
                              new_v=None, k_scale=None, v_scale=None,
                              quant=None):
    """:func:`paged_attention_decode` sharded over a tensor-parallel mesh
    axis: kv-heads (pool dim 1) split across ``axis`` and each shard runs
    the unmodified kernel on its local head group — the grid's
    (batch, kv_head, max_blocks) shape makes kv-heads the natural shard
    dim, so per-shard programs are byte-identical to the single-chip
    kernel at Hkv/ntp heads. Block tables and seq_lens ride in REPLICATED
    (the allocator is host-global); q's head dim shards alongside
    (kv-head-major GQA layout: q heads [h*G, (h+1)*G) follow kv head h,
    so an even kv-head split carries its q groups with it). No collective
    is issued — attention output heads stay sharded and the caller's
    o_proj (row-parallel) reduces them. Quantized pools (``quant``)
    thread their per-(block, head) scale arrays with the SAME kv-head
    sharding (scale dim 1 == pool dim 1), so each shard quantizes its
    own heads — the per-head absmax rule makes the sharded result
    bit-identical to single-chip."""
    from jax.sharding import PartitionSpec as P

    write_new = new_k is not None
    q_spec = P(None, axis, None)
    pool_spec = P(None, axis, None, None)
    scale_spec = P(None, axis)
    in_specs = [q_spec, pool_spec, pool_spec, P(), P()]
    out_specs = [q_spec, pool_spec, pool_spec] if write_new else q_spec
    args = [q, k_pool, v_pool, block_tables, seq_lens]
    if quant:
        in_specs += [scale_spec, scale_spec]
        args += [k_scale, v_scale]
        if write_new:
            out_specs += [scale_spec, scale_spec]
    if write_new:
        in_specs += [P(None, axis, None), P(None, axis, None)]
        args += [new_k, new_v]

    def body(q_s, k_s, v_s, tables, lens, *rest):
        if quant:
            ks_s, vs_s, *rest = rest
        else:
            ks_s = vs_s = None
        nk_s, nv_s = rest if rest else (None, None)
        return paged_attention_decode(q_s, k_s, v_s, tables, lens,
                                      scale=scale, new_k=nk_s, new_v=nv_s,
                                      k_scale=ks_s, v_scale=vs_s,
                                      quant=quant)

    return _tp_shard_map(body, mesh, axis, in_specs, out_specs)(*args)


def paged_attention_append_tp(q, k_pool, v_pool, block_tables, seq_lens,
                              q_lens, new_k, new_v, mesh, axis="tp",
                              scale=None, k_scale=None, v_scale=None,
                              quant=None):
    """:func:`paged_attention_append` sharded over a tensor-parallel mesh
    axis — the mixed prefill+decode step's kernel under the TP serving
    engine. Same layout contract as the decode wrapper: pools/new-KV/q
    (and, quantized, the per-(block, head) scale arrays) shard on their
    head dims, tables/seq_lens/q_lens replicated, output heads stay
    sharded for the row-parallel o_proj to reduce."""
    from jax.sharding import PartitionSpec as P

    pool_spec = P(None, axis, None, None)
    scale_spec = P(None, axis)
    q_spec = P(None, None, axis, None)          # [B, S, Hq, D]
    in_specs = [q_spec, pool_spec, pool_spec, P(), P(), P()]
    out_specs = [q_spec, pool_spec, pool_spec]
    args = [q, k_pool, v_pool, block_tables, seq_lens, q_lens]
    if quant:
        in_specs += [scale_spec, scale_spec]
        out_specs += [scale_spec, scale_spec]
        args += [k_scale, v_scale]
    in_specs += [q_spec, q_spec]                # new_k/new_v [B, S, Hkv, D]
    args += [new_k, new_v]

    def body(q_s, k_s, v_s, tables, lens, qlens, *rest):
        if quant:
            ks_s, vs_s, nk_s, nv_s = rest
        else:
            ks_s = vs_s = None
            nk_s, nv_s = rest
        return paged_attention_append(q_s, k_s, v_s, tables, lens, qlens,
                                      nk_s, nv_s, scale=scale,
                                      k_scale=ks_s, v_scale=vs_s,
                                      quant=quant)

    return _tp_shard_map(body, mesh, axis, in_specs, out_specs)(*args)


def _last_live(lens_ref, b, bs, mb):
    """Logical index of the block holding position ``lens[b]`` (where the
    decode step's new token lands), clamped into the table. lax.div keeps
    i32 under x64 (a plain ``//`` promotes and breaks Mosaic's lowering)."""
    return jnp.minimum(jax.lax.div(lens_ref[b], np.int32(bs)),
                       np.int32(mb - 1))


def _q_index_map(b, h, j, tables_ref, lens_ref):
    return (b, h, Z, Z)


def _kv_index_map(bs, mb):
    def im(b, h, j, tables_ref, lens_ref):
        j_last = _last_live(lens_ref, b, bs, mb)
        jj = jnp.minimum(j, j_last)          # dead tail re-maps to last live
        phys = tables_ref[b, jj]
        return (jnp.maximum(phys, Z), h, Z, Z)   # -1 -> block 0 (masked read)
    return im


def _new_kv_index_map(b, h, j, tables_ref, lens_ref):
    return (b, h, Z)


def _pool_out_index_map(bs, mb, nb):
    """Fused-write destination: the last live block of sequence b. A -1
    (unallocated) target must not clobber a real block — route it to the
    pool's trailing scratch block instead (the analog of the XLA path's
    out-of-range ``mode="drop"`` scatter)."""
    def im(b, h, j, tables_ref, lens_ref):
        phys = tables_ref[b, _last_live(lens_ref, b, bs, mb)]
        return (jnp.where(phys < Z, np.int32(nb - 1), phys), h, Z, Z)
    return im


def _scale_index_map(bs, mb):
    """Per-(block, head) scale READ window of one grid step: the same
    physical block the K/V BlockSpec maps (2-D: scales are
    [num_blocks, Hkv])."""
    def im(b, h, j, tables_ref, lens_ref):
        j_last = _last_live(lens_ref, b, bs, mb)
        jj = jnp.minimum(j, j_last)
        return (jnp.maximum(tables_ref[b, jj], Z), h)
    return im


def _scale_out_index_map(bs, mb, nb):
    """Scale WRITE destination of the fused quantized write: the same
    last-live (or scratch) block the pool out map routes to."""
    def im(b, h, j, tables_ref, lens_ref):
        phys = tables_ref[b, _last_live(lens_ref, b, bs, mb)]
        return (jnp.where(phys < Z, np.int32(nb - 1), phys), h)
    return im


def _decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, *rest, scale,
                   bs, mb, write_new, quant=None, d_head=None):
    if quant:
        if write_new:
            (ks_ref, vs_ref, nk_ref, nv_ref, o_ref, ko_ref, vo_ref,
             kso_ref, vso_ref, m_ref, l_ref, acc_ref) = rest
        else:
            ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    elif write_new:
        nk_ref, nv_ref, o_ref, ko_ref, vo_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    bs_i = np.int32(bs)
    L = lens_ref[b]
    j_last = _last_live(lens_ref, b, bs, mb)
    jj = jnp.minimum(j, j_last)
    phys = tables_ref[b, jj]
    # dead tail (past the live blocks) and unallocated (-1) entries skip
    # compute; their clamped reads are either unused or masked below
    live = (j <= j_last) & (phys >= Z)

    @pl.when(j == Z)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    k_blk = k_ref[0, 0]                                   # [bs, D]
    v_blk = v_ref[0, 0]
    if quant:
        # in-VMEM dequant right after the (2x/4x smaller) block DMA: the
        # attention math below is the plain f32 path
        k_blk = kv_unpack(k_blk, quant, d_head) * ks_ref[0, 0]
        v_blk = kv_unpack(v_blk, quant, d_head) * vs_ref[0, 0]
    if write_new:
        # merge the new token's K/V into the last live block in VMEM: the
        # attention below sees it this step, and the merged block writes
        # back through the aliased pool outputs (in-place)
        slot = L - j_last * bs_i
        row = jax.lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
        sel = (row == slot) & (j == j_last)
        k_blk = jnp.where(sel, nk_ref[0, 0][None, :].astype(k_blk.dtype),
                          k_blk)
        v_blk = jnp.where(sel, nv_ref[0, 0][None, :].astype(v_blk.dtype),
                          v_blk)
        if quant:
            # in-VMEM re-quantize of the merged block: new per-head
            # absmax scale, int payload + scale back through the aliased
            # outputs — no dequantized block reaches HBM. DEAD ROWS
            # (positions past the new token, i.e. stale content of a
            # reused freed block) are ZEROED first: attention always
            # masks them, but an unmasked absmax would let a dirty
            # block's garbage inflate the scale and crush the live
            # token's resolution — quantized output must not depend on
            # pool-reuse history. Attention then reads the
            # ROUND-TRIPPED values (what the pool stores), so this
            # step's logits equal a later re-read of the same cache —
            # and match the dense fallback bit-for-bit.
            dead = (j == j_last) & (row > slot)
            k_blk = jnp.where(dead, np.float32(0.0), k_blk)
            v_blk = jnp.where(dead, np.float32(0.0), v_blk)
            ks_new = kv_block_scale(k_blk, quant, axes=(0, 1))
            vs_new = kv_block_scale(v_blk, quant, axes=(0, 1))
            kq_new = kv_quantize(k_blk, ks_new, quant)
            vq_new = kv_quantize(v_blk, vs_new, quant)
            k_blk = jnp.where(j == j_last,
                              kv_unpack(kq_new, quant, d_head) * ks_new,
                              k_blk)
            v_blk = jnp.where(j == j_last,
                              kv_unpack(vq_new, quant, d_head) * vs_new,
                              v_blk)

        @pl.when(j == j_last)
        def _store_block():
            if quant:
                kso_ref[0, 0] = ks_new
                vso_ref[0, 0] = vs_new
                ko_ref[0, 0] = kq_new
                vo_ref[0, 0] = vq_new
            else:
                ko_ref[0, 0] = k_blk.astype(ko_ref.dtype)
                vo_ref[0, 0] = v_blk.astype(vo_ref.dtype)

    g = q_ref.shape[2]

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)    # [G, D]
        s = jax.lax.dot_general(q, k_blk.astype(jnp.float32),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [G, bs]
        pos = jj * bs_i + jax.lax.broadcasted_iota(jnp.int32, (g, bs), 1)
        s = jnp.where(pos <= L, s, NEG_INF)          # include new token at L
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == np.int32(mb - 1))
    def _finalize():
        l = jnp.maximum(l_ref[...], np.float32(1e-30))
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention_decode(q, k_pool, v_pool, block_tables, seq_lens,
                           scale=None, new_k=None, new_v=None,
                           k_scale=None, v_scale=None, quant=None):
    """One decode step of paged attention, straight off the block pools.

    q: [B, Hq, D] (this step's query, one token per sequence);
    k_pool/v_pool: [num_blocks, Hkv, block_size, D] physical pools;
    block_tables: [B, max_blocks] logical->physical (-1 = unallocated);
    seq_lens: [B] tokens already cached — the new token sits at position
    ``seq_lens[b]`` and attention covers positions <= seq_lens[b].

    Hq must be a multiple of Hkv (GQA: each kv head serves Hq/Hkv q heads).

    new_k/new_v ([B, Hkv, D], both or neither): fuse the new token's K/V
    write into the kernel — returns (out, k_pool, v_pool) with the pools
    updated in place (aliased). Without them the caller must have already
    scattered the new token into the pools; returns out only.
    Out: [B, Hq, D] in q.dtype (fp32 accumulation inside).

    ``quant="int8"|"int4"`` + ``k_scale``/``v_scale`` [num_blocks, Hkv]
    fp32: the pools are QUANTIZED storage (int4 nibble-packed on D, so
    the pool's last dim is :func:`kv_packed_dim`). Each block dequantizes
    in VMEM during the walk; the fused write re-quantizes the merged
    block in VMEM (new per-head absmax scale computed in-kernel) and the
    scale arrays return updated alongside the pools:
    ``(out, k_pool, v_pool, k_scale, v_scale)``.
    """
    B, Hq, D = q.shape
    NB, Hkv, BS, Dk = k_pool.shape
    if quant:
        assert k_scale is not None and v_scale is not None
        assert Dk == kv_packed_dim(D, quant), (q.shape, k_pool.shape, quant)
    else:
        assert k_scale is None and v_scale is None
        assert D == Dk, (q.shape, k_pool.shape)
    assert Hq % Hkv == 0, f"GQA needs Hq % Hkv == 0, got {Hq=} {Hkv=}"
    G = Hq // Hkv
    MB = block_tables.shape[1]
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    write_new = new_k is not None
    assert (new_v is not None) == write_new

    q4 = q.reshape(B, Hkv, G, D)
    tables = block_tables.astype(jnp.int32)
    lens = seq_lens.astype(jnp.int32)

    in_specs = [
        pl.BlockSpec((1, 1, G, D), _q_index_map),
        pl.BlockSpec((1, 1, BS, Dk), _kv_index_map(BS, MB)),
        pl.BlockSpec((1, 1, BS, Dk), _kv_index_map(BS, MB)),
    ]
    out_specs = [pl.BlockSpec((1, 1, G, D), _q_index_map)]
    out_shape = [jax.ShapeDtypeStruct((B, Hkv, G, D), q.dtype)]
    inputs = [tables, lens, q4, k_pool, v_pool]
    io_aliases = {}
    if quant:
        scale_spec = pl.BlockSpec((1, 1), _scale_index_map(BS, MB))
        in_specs += [scale_spec, scale_spec]
        inputs += [k_scale.astype(jnp.float32),
                   v_scale.astype(jnp.float32)]
    if write_new:
        # new-token K/V arrives in the model dtype regardless of pool
        # quantization — the kernel quantizes in VMEM
        nk_dt = k_pool.dtype if not quant else new_k.dtype
        in_specs += [pl.BlockSpec((1, 1, D), _new_kv_index_map),
                     pl.BlockSpec((1, 1, D), _new_kv_index_map)]
        inputs += [new_k.reshape(B, Hkv, D).astype(nk_dt),
                   new_v.reshape(B, Hkv, D).astype(nk_dt)]
        pool_spec = pl.BlockSpec((1, 1, BS, Dk),
                                 _pool_out_index_map(BS, MB, NB))
        out_specs += [pool_spec, pool_spec]
        out_shape += [jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                      jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype)]
        # flat input indices INCLUDE the scalar-prefetch operands
        io_aliases = {3: 1, 4: 2}
        if quant:
            scale_out = pl.BlockSpec((1, 1),
                                     _scale_out_index_map(BS, MB, NB))
            out_specs += [scale_out, scale_out]
            out_shape += [jax.ShapeDtypeStruct((NB, Hkv), jnp.float32),
                          jax.ShapeDtypeStruct((NB, Hkv), jnp.float32)]
            io_aliases = {3: 1, 4: 2, 5: 3, 6: 4}

    kernel = functools.partial(_decode_kernel, scale=scale, bs=BS, mb=MB,
                               write_new=write_new, quant=quant, d_head=D)
    outs = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv, MB),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),    # running max m
                pltpu.VMEM((G, 1), jnp.float32),    # running normalizer l
                pltpu.VMEM((G, D), jnp.float32),    # output accumulator
            ],
        ),
        out_shape=out_shape,
        input_output_aliases=io_aliases,
        # every dim sequential: scratch carries over blocks, and the fused
        # write's clamped scratch-block destinations may collide across
        # batch windows — megacore parallelism would race them
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*inputs)
    out = outs[0].reshape(B, Hq, D)
    if write_new:
        if quant:
            return out, outs[1], outs[2], outs[3], outs[4]
        return out, outs[1], outs[2]
    return out


# ---------------------------------------------------------------------------
# append attention: q_len = chunk (the fused prefill+decode mixed step)
# ---------------------------------------------------------------------------

def _apd_blk(lens_ref, qlens_ref, b, bs, mb, last):
    """Block index of the append window's first (``last=False``) or last
    (``last=True``) written position, clamped into the table. q_lens == 0
    degenerates both to the block holding ``lens`` (nothing is written;
    that block is stored back unchanged so the aliased out window never
    copies out undefined VMEM)."""
    pos = lens_ref[b] + (jnp.maximum(qlens_ref[b] - 1, 0) if last else 0)
    return jnp.minimum(jax.lax.div(pos, np.int32(bs)), np.int32(mb - 1))


def _apd_q_index_map(b, h, j, tables_ref, lens_ref, qlens_ref):
    return (b, h, Z, Z)


def _apd_kv_index_map(bs, mb):
    def im(b, h, j, tables_ref, lens_ref, qlens_ref):
        j_last = _apd_blk(lens_ref, qlens_ref, b, bs, mb, True)
        jj = jnp.minimum(j, j_last)          # dead tail re-maps to last live
        return (jnp.maximum(tables_ref[b, jj], Z), h, Z, Z)
    return im


def _apd_new_index_map(b, h, j, tables_ref, lens_ref, qlens_ref):
    return (b, h, Z, Z)


def _apd_pool_out_index_map(bs, mb, nb):
    """Fused-write destinations: the blocks overlapping the append window
    [lens, lens+q_lens). Steps outside the window pin to its boundary
    blocks, so their mapping never changes and no copy is issued — only
    the overlapped blocks (each merged + stored in the kernel) pay a
    write. -1 targets (a freed slot's wiped table row) route to the
    pool's trailing scratch block, as in the decode kernel."""
    def im(b, h, j, tables_ref, lens_ref, qlens_ref):
        w0 = _apd_blk(lens_ref, qlens_ref, b, bs, mb, False)
        w1 = _apd_blk(lens_ref, qlens_ref, b, bs, mb, True)
        phys = tables_ref[b, jnp.clip(j, w0, w1)]
        return (jnp.where(phys < Z, np.int32(nb - 1), phys), h, Z, Z)
    return im


def _apd_scale_index_map(bs, mb):
    """Append-form scale READ window: the same block the K/V spec maps
    (2-D — scales are [num_blocks, Hkv])."""
    def im(b, h, j, tables_ref, lens_ref, qlens_ref):
        j_last = _apd_blk(lens_ref, qlens_ref, b, bs, mb, True)
        jj = jnp.minimum(j, j_last)
        return (jnp.maximum(tables_ref[b, jj], Z), h)
    return im


def _apd_scale_out_index_map(bs, mb, nb):
    """Append-form scale WRITE destinations: the same window blocks the
    pool out map routes to."""
    def im(b, h, j, tables_ref, lens_ref, qlens_ref):
        w0 = _apd_blk(lens_ref, qlens_ref, b, bs, mb, False)
        w1 = _apd_blk(lens_ref, qlens_ref, b, bs, mb, True)
        phys = tables_ref[b, jnp.clip(j, w0, w1)]
        return (jnp.where(phys < Z, np.int32(nb - 1), phys), h)
    return im


def _append_kernel(tables_ref, lens_ref, qlens_ref, q_ref, k_ref, v_ref,
                   *rest, scale, bs, mb, s_chunk, quant=None, d_head=None):
    if quant:
        (ks_ref, vs_ref, nk_ref, nv_ref, o_ref, ko_ref, vo_ref, kso_ref,
         vso_ref, m_ref, l_ref, acc_ref) = rest
    else:
        (nk_ref, nv_ref, o_ref, ko_ref, vo_ref, m_ref, l_ref,
         acc_ref) = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    bs_i = np.int32(bs)
    L = lens_ref[b]
    QL = qlens_ref[b]
    j_last = _apd_blk(lens_ref, qlens_ref, b, bs, mb, True)
    w0 = _apd_blk(lens_ref, qlens_ref, b, bs, mb, False)
    jj = jnp.minimum(j, j_last)
    phys = tables_ref[b, jj]
    live = (j <= j_last) & (phys >= Z) & (QL > Z)

    @pl.when(j == Z)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    k_blk = k_ref[0, 0]                                       # [bs, D]
    v_blk = v_ref[0, 0]
    if quant:
        # in-VMEM dequant right after the block DMA (decode-kernel rule)
        k_blk = kv_unpack(k_blk, quant, d_head) * ks_ref[0, 0]
        v_blk = kv_unpack(v_blk, quant, d_head) * vs_ref[0, 0]
    # merge the chunk rows that land in THIS block into it in VMEM: block
    # row r holds chunk index i = j*bs + r - lens when 0 <= i < q_lens.
    # The gather is expressed as a one-hot selection matmul ([bs, S] @
    # [S, D] — MXU-friendly; Mosaic has no per-row dynamic gather), so
    # attention sees the whole new chunk this step and the merged block
    # writes back through the aliased pool outputs.
    row = jax.lax.broadcasted_iota(jnp.int32, (bs, s_chunk), 0)
    ci = jax.lax.broadcasted_iota(jnp.int32, (bs, s_chunk), 1)
    sel = ((jj * bs_i + row - L) == ci) & (ci < QL) & (ci >= Z)
    has_new = jnp.any(sel, axis=1, keepdims=True)             # [bs, 1]
    sel_f = sel.astype(jnp.float32)
    merged_k = jax.lax.dot_general(
        sel_f, nk_ref[0, 0].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    merged_v = jax.lax.dot_general(
        sel_f, nv_ref[0, 0].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    k_blk = jnp.where(has_new, merged_k.astype(k_blk.dtype), k_blk)
    v_blk = jnp.where(has_new, merged_v.astype(v_blk.dtype), v_blk)
    in_window = (j >= w0) & (j <= j_last)
    if quant:
        # in-VMEM re-quantize of each window block: old rows re-round
        # under the merged block's new absmax scale (drift-free when the
        # max is unchanged: absmax quantization round-trips its own grid
        # exactly). DEAD ROWS — positions at or past the window's new
        # end (stale content of a reused freed block) — are ZEROED
        # before the scale so a dirty block's garbage can't inflate it
        # (decode-kernel rule; quantized output must not depend on
        # pool-reuse history). A q_lens==0 slot writes nothing: its
        # boundary block stores back its ORIGINAL payload + scale (the
        # unquantized path's "stored back unchanged" contract — no
        # zeroing, no re-round). Attention reads the ROUND-TRIPPED
        # values — this step's logits equal a later re-read of the
        # stored cache, and match the dense fallback bit-for-bit.
        dead = in_window & ((jj * bs_i + row[:, :1]) >= (L + QL))
        k_blk = jnp.where(dead, np.float32(0.0), k_blk)
        v_blk = jnp.where(dead, np.float32(0.0), v_blk)
        ks_new = kv_block_scale(k_blk, quant, axes=(0, 1))
        vs_new = kv_block_scale(v_blk, quant, axes=(0, 1))
        kq_new = kv_quantize(k_blk, ks_new, quant)
        vq_new = kv_quantize(v_blk, vs_new, quant)
        kq_store = jnp.where(QL > Z, kq_new, k_ref[0, 0])
        vq_store = jnp.where(QL > Z, vq_new, v_ref[0, 0])
        ks_store = jnp.where(QL > Z, ks_new, ks_ref[0, 0])
        vs_store = jnp.where(QL > Z, vs_new, vs_ref[0, 0])
        k_blk = jnp.where(in_window,
                          kv_unpack(kq_new, quant, d_head) * ks_new, k_blk)
        v_blk = jnp.where(in_window,
                          kv_unpack(vq_new, quant, d_head) * vs_new, v_blk)

    @pl.when(in_window)
    def _store_block():
        if quant:
            kso_ref[0, 0] = ks_store
            vso_ref[0, 0] = vs_store
            ko_ref[0, 0] = kq_store
            vo_ref[0, 0] = vq_store
        else:
            ko_ref[0, 0] = k_blk.astype(ko_ref.dtype)
            vo_ref[0, 0] = v_blk.astype(vo_ref.dtype)

    g_s = q_ref.shape[2]                                      # G * S rows

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32) * np.float32(scale)  # [G*S, D]
        s = jax.lax.dot_general(q, k_blk.astype(jnp.float32),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        # query row r is chunk index i = r % S at absolute position
        # lens + i; causal against pooled history AND its own chunk
        r = jax.lax.broadcasted_iota(jnp.int32, (g_s, bs), 0)
        i_chunk = jax.lax.rem(r, np.int32(s_chunk))
        pos = jj * bs_i + jax.lax.broadcasted_iota(jnp.int32, (g_s, bs), 1)
        s = jnp.where(pos <= L + i_chunk, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == np.int32(mb - 1))
    def _finalize():
        l = jnp.maximum(l_ref[...], np.float32(1e-30))
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention_append(q, k_pool, v_pool, block_tables, seq_lens,
                           q_lens, new_k, new_v, scale=None, k_scale=None,
                           v_scale=None, quant=None):
    """Append attention off the block pools: one fused prefill+decode step.

    q: [B, S, Hq, D] — up to S new positions per sequence (rows past
    ``q_lens[b]`` are padding; their outputs are garbage the caller
    ignores); k_pool/v_pool: [num_blocks, Hkv, block_size, D];
    block_tables: [B, max_blocks]; seq_lens: [B] tokens already cached —
    sequence b's chunk occupies positions [seq_lens[b],
    seq_lens[b]+q_lens[b]); q_lens: [B] valid rows (0 = inactive slot:
    no compute, no write). new_k/new_v: [B, S, Hkv, D], the chunk's K/V
    — always fused-written (blocks overlapping the window are merged in
    VMEM, attention sees the chunk without a prior scatter round-trip,
    and write back through aliased outputs).

    Query row i of sequence b attends causally: pooled positions plus its
    own chunk prefix (kv position <= seq_lens[b] + i). The caller must
    have blocks allocated to cover the window (the fused scheduler does);
    a -1 target writes to the pool's trailing scratch block.

    Returns (out [B, S, Hq, D] in q.dtype, k_pool, v_pool).

    ``quant`` + ``k_scale``/``v_scale`` [num_blocks, Hkv]: quantized
    pools exactly as in :func:`paged_attention_decode` — blocks dequant
    in VMEM for the walk, every window block re-quantizes in VMEM with
    its new per-head absmax scale, and the return grows to
    ``(out, k_pool, v_pool, k_scale, v_scale)``.
    """
    B, S, Hq, D = q.shape
    NB, Hkv, BS, Dk = k_pool.shape
    if quant:
        assert k_scale is not None and v_scale is not None
        assert Dk == kv_packed_dim(D, quant), (q.shape, k_pool.shape, quant)
    else:
        assert k_scale is None and v_scale is None
        assert D == Dk, (q.shape, k_pool.shape)
    assert Hq % Hkv == 0, f"GQA needs Hq % Hkv == 0, got {Hq=} {Hkv=}"
    G = Hq // Hkv
    MB = block_tables.shape[1]
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)

    # [B, S, Hq, D] -> [B, Hkv, G*S, D]: row r = g*S + i (head-major, so
    # the q-head split matches the decode kernel's (Hkv, G) grouping)
    nk_dt = k_pool.dtype if not quant else new_k.dtype
    q4 = jnp.transpose(q, (0, 2, 1, 3)).reshape(B, Hkv, G * S, D)
    nk = jnp.transpose(new_k, (0, 2, 1, 3)).astype(nk_dt)
    nv = jnp.transpose(new_v, (0, 2, 1, 3)).astype(nk_dt)
    tables = block_tables.astype(jnp.int32)
    lens = seq_lens.astype(jnp.int32)
    qlens = q_lens.astype(jnp.int32)

    pool_spec = pl.BlockSpec((1, 1, BS, Dk),
                             _apd_pool_out_index_map(BS, MB, NB))
    in_specs = [
        pl.BlockSpec((1, 1, G * S, D), _apd_q_index_map),
        pl.BlockSpec((1, 1, BS, Dk), _apd_kv_index_map(BS, MB)),
        pl.BlockSpec((1, 1, BS, Dk), _apd_kv_index_map(BS, MB)),
    ]
    out_specs = [pl.BlockSpec((1, 1, G * S, D), _apd_q_index_map),
                 pool_spec, pool_spec]
    out_shape = [jax.ShapeDtypeStruct((B, Hkv, G * S, D), q.dtype),
                 jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                 jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype)]
    inputs = [tables, lens, qlens, q4, k_pool, v_pool]
    # flat input indices INCLUDE the scalar-prefetch operands
    io_aliases = {4: 1, 5: 2}
    if quant:
        scale_in = pl.BlockSpec((1, 1), _apd_scale_index_map(BS, MB))
        scale_out = pl.BlockSpec((1, 1),
                                 _apd_scale_out_index_map(BS, MB, NB))
        in_specs += [scale_in, scale_in]
        out_specs += [scale_out, scale_out]
        out_shape += [jax.ShapeDtypeStruct((NB, Hkv), jnp.float32),
                      jax.ShapeDtypeStruct((NB, Hkv), jnp.float32)]
        inputs += [k_scale.astype(jnp.float32),
                   v_scale.astype(jnp.float32)]
        io_aliases = {4: 1, 5: 2, 6: 3, 7: 4}
    in_specs += [pl.BlockSpec((1, 1, S, D), _apd_new_index_map),
                 pl.BlockSpec((1, 1, S, D), _apd_new_index_map)]
    inputs += [nk, nv]

    kernel = functools.partial(_append_kernel, scale=scale, bs=BS, mb=MB,
                               s_chunk=S, quant=quant, d_head=D)
    outs = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, Hkv, MB),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((G * S, 1), jnp.float32),   # running max m
                pltpu.VMEM((G * S, 1), jnp.float32),   # running norm l
                pltpu.VMEM((G * S, D), jnp.float32),   # output accumulator
            ],
        ),
        out_shape=out_shape,
        input_output_aliases=io_aliases,
        # sequential everywhere: scratch carries over blocks and clamped
        # write destinations may collide across batch windows
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*inputs)
    out = outs[0].reshape(B, Hkv, G, S, D)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(B, S, Hq, D)
    if quant:
        return out, outs[1], outs[2], outs[3], outs[4]
    return out, outs[1], outs[2]
