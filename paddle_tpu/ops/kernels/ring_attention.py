"""Ring attention — blockwise context parallelism over a mesh axis.

The reference ships no in-core ring attention (SURVEY.md §5.7: PaddleNLP implements
"RingFlashAttention" out-of-tree on top of the `sep` hybrid axis,
fleet/meta_parallel/segment_parallel.py:26). Here it is first-class and TPU-native:
K/V shards rotate around the ring with `jax.lax.ppermute` (ICI neighbor exchange),
each step computes one attention block and merges it into the running output with a
numerically-stable log-sum-exp combine. The whole loop is a `lax.scan`, so XLA
overlaps the ppermute with the block matmuls, and `jax.checkpoint` on the per-step
body keeps backward memory at one block of logits.

Causal load balancing uses the zigzag layout: rank r holds sequence chunks
(r, 2N-1-r), so every rank does the same causal work. Masking is driven by global
position indices, so contiguous and zigzag layouts share one code path.

All functions here operate on raw jax arrays INSIDE shard_map (one shard per rank),
layout [B, S_local, H, D]. User-facing wrappers live in
paddle_tpu/distributed/fleet/context_parallel.py.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30  # finite stand-in for -inf: keeps exp/where arithmetic NaN-free


def _block_attn(q, k, v, mask, scale):
    """One attention block, returning (normalized out, lse) in fp32 stats.

    q: [B, Lq, H, D]; k, v: [B, Lk, KVH, D]; mask: [Lq, Lk] bool (True = attend).
    Handles GQA by repeating KV heads. Rows with no visible keys produce
    out = 0, lse = ~-inf, so they contribute nothing to the ring merge.
    """
    h, kvh = q.shape[2], k.shape[2]
    if kvh != h:
        k = jnp.repeat(k, h // kvh, axis=2)
        v = jnp.repeat(v, h // kvh, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(mask[None, None], logits.astype(jnp.float32), _NEG_INF)
    m = jnp.max(logits, axis=-1)                      # [B,H,Lq]
    row_dead = m <= _NEG_INF / 2
    m_safe = jnp.where(row_dead, 0.0, m)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                           # [B,H,Lq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = o / jnp.swapaxes(l_safe, 1, 2)[..., None].astype(o.dtype)
    lse = jnp.where(row_dead, _NEG_INF, m_safe + jnp.log(l_safe))
    return o, lse                                     # o: [B,Lq,H,D], lse: [B,H,Lq]


def _merge(o1, lse1, o2, lse2):
    """Combine two normalized partial-softmax results (flash-attention merge)."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    w1 = jnp.where(lse1 <= _NEG_INF / 2, 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(lse2 <= _NEG_INF / 2, 0.0, jnp.exp(lse2 - m_safe))
    tot = w1 + w2
    tot_safe = jnp.where(tot == 0.0, 1.0, tot)
    wb1 = jnp.swapaxes(w1 / tot_safe, 1, 2)[..., None]   # [B,Lq,H,1]
    wb2 = jnp.swapaxes(w2 / tot_safe, 1, 2)[..., None]
    o = o1 * wb1.astype(o1.dtype) + o2 * wb2.astype(o2.dtype)
    lse = jnp.where(tot == 0.0, _NEG_INF, m_safe + jnp.log(tot_safe))
    return o, lse


def zigzag_positions(axis_index, n_ranks, local_len):
    """Global positions of this rank's rows under the zigzag (balanced) layout.

    Rank r holds chunks (r, 2N-1-r) of size local_len//2 each, so causal work is
    uniform across ranks. local_len must be even.
    """
    c = local_len // 2
    lo = axis_index * c + jnp.arange(c)
    hi = (2 * n_ranks - 1 - axis_index) * c + jnp.arange(c)
    return jnp.concatenate([lo, hi])


def contiguous_positions(axis_index, n_ranks, local_len):
    return axis_index * local_len + jnp.arange(local_len)


def ring_attention(q, k, v, axis_name, causal=False, scale=None, balanced=False):
    """Ring attention over mesh axis `axis_name`; call inside shard_map.

    q: [B, S_local, H, D]; k, v: [B, S_local, KVH, D] — each rank's sequence shard.
    `balanced=True` expects inputs in the zigzag layout (see shard_zigzag) and only
    matters for causal masking. Fully differentiable (scan + ppermute transpose).
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    lq, lk = q.shape[1], k.shape[1]
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    pos_fn = zigzag_positions if balanced else contiguous_positions
    qpos = pos_fn(my, n, lq)

    perm = [(i, (i + 1) % n) for i in range(n)]

    @jax.checkpoint
    def block(q, k, v, kv_idx):
        if causal:
            kvpos = pos_fn(kv_idx, n, lk)
            mask = qpos[:, None] >= kvpos[None, :]
        else:
            mask = jnp.ones((lq, lk), bool)
        return _block_attn(q, k, v, mask, scale)

    # step 0 on the local KV shard, then n-1 rotate-and-accumulate steps —
    # exactly n-1 ppermutes, none wasted on a discarded final rotation
    o0, lse0 = block(q, k, v, my)

    def step(carry, s):
        kc, vc, o_acc, lse_acc = carry
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        kv_idx = (my - s) % n
        o_b, lse_b = block(q, kc, vc, kv_idx)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_b, lse_b)
        return (kc, vc, o_acc, lse_acc), None

    (_, _, o, _), _ = jax.lax.scan(step, (k, v, o0, lse0), jnp.arange(1, n))
    return o


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      attn_fn=None):
    """Ulysses (DeepSpeed-style) all-to-all attention; call inside shard_map.

    Swaps the sequence shard for a head shard with `lax.all_to_all`, runs FULL
    attention locally on n_heads/N heads (flash kernel on TPU), and swaps back.
    Requires H (and KVH) divisible by the axis size.
    """
    # [B, S/N, H, D] -> [B, S, H/N, D]
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if attn_fn is None:
        from ...core.flags import flag_value
        from ...nn.functional import attention as _  # registers the flag
        # same routing rules as scaled_dot_product_attention: Pallas only on
        # TPU, only when the flag allows it, and causal sq!=sk (top-left vs
        # bottom-right alignment mismatch) goes to the exact path
        use_pallas = (jax.default_backend() == "tpu"
                      and flag_value("use_pallas_flash_attention")
                      and (not causal or qh.shape[1] == kh.shape[1]))
        if use_pallas:
            from .flash_attention import flash_attention_fwd
            o = flash_attention_fwd(qh, kh, vh, causal=causal, scale=scale)
        else:
            d = q.shape[-1]
            s = scale if scale is not None else 1.0 / math.sqrt(d)
            lq, lk = qh.shape[1], kh.shape[1]
            # bottom-right aligned causal (paddle semantics, _sdpa_reference)
            mask = (jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq) if causal
                    else jnp.ones((lq, lk), bool))
            o, _ = _block_attn(qh, kh, vh, mask, s)
    else:
        o = attn_fn(qh, kh, vh)
    # [B, S, H/N, D] -> [B, S/N, H, D]
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)
