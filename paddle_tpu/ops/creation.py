"""Tensor creation ops (paddle.tensor.creation analog).

Reference: python/paddle/tensor/creation.py; kernels in paddle/phi/kernels
(full_kernel.h, arange, eye, ...). Here every creation lowers to one jnp call; device
placement is XLA's default-device behavior (Place model in core/device.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, dispatch, register_op
from ..core import random as _random


def _dt(dtype, default=None):
    if dtype is None:
        return default
    return dtypes.convert_dtype(dtype)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor — python/paddle/tensor/creation.py:to_tensor analog."""
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(dtypes.convert_dtype(dtype))
        return Tensor(v, stop_gradient=stop_gradient)
    if dtype is None:
        # match paddle: python floats -> default float dtype, ints -> int64
        if isinstance(data, bool):
            dtype = dtypes.bool_
        elif isinstance(data, int):
            dtype = dtypes.int64
        elif isinstance(data, float):
            dtype = dtypes.get_default_dtype()
        elif isinstance(data, (list, tuple)):
            arr = np.asarray(data)
            if arr.dtype == np.float64:
                dtype = dtypes.get_default_dtype()
            elif arr.dtype == np.int32 or arr.dtype == np.int64:
                dtype = dtypes.int64
        v = jnp.asarray(data, dtype=_dt(dtype))
    else:
        v = jnp.asarray(data, dtype=dtypes.convert_dtype(dtype))
    return Tensor(v, stop_gradient=stop_gradient)


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value if isinstance(s, Tensor) else s) for s in shape)


def zeros(shape, dtype=None) -> Tensor:
    return Tensor(jnp.zeros(_shape_tuple(shape), _dt(dtype, dtypes.get_default_dtype())))


def ones(shape, dtype=None) -> Tensor:
    return Tensor(jnp.ones(_shape_tuple(shape), _dt(dtype, dtypes.get_default_dtype())))


def full(shape, fill_value, dtype=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = dtypes.bool_
        elif isinstance(fill_value, int):
            dtype = dtypes.int64
        else:
            dtype = dtypes.get_default_dtype()
    return Tensor(jnp.full(_shape_tuple(shape), fill_value, dtypes.convert_dtype(dtype)))


def empty(shape, dtype=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None) -> Tensor:
    return Tensor(jnp.zeros_like(x._value if isinstance(x, Tensor) else x, dtype=_dt(dtype)))


def ones_like(x, dtype=None) -> Tensor:
    return Tensor(jnp.ones_like(x._value if isinstance(x, Tensor) else x, dtype=_dt(dtype)))


def full_like(x, fill_value, dtype=None) -> Tensor:
    return Tensor(jnp.full_like(x._value if isinstance(x, Tensor) else x,
                                fill_value, dtype=_dt(dtype)))


def empty_like(x, dtype=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = _scalar(start), _scalar(end), _scalar(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (dtypes.int64 if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
                 else dtypes.get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None) -> Tensor:
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(_scalar(start), _scalar(stop), int(_scalar(num)),
                               dtype=_dt(dtype, dtypes.get_default_dtype())))


def logspace(start, stop, num, base=10.0, dtype=None) -> Tensor:
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=_dt(dtype, dtypes.get_default_dtype())))


def eye(num_rows, num_columns=None, dtype=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype, dtypes.get_default_dtype())))


@register_op
def _tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register_op
def _triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril(x, diagonal=0):
    return _tril(x, diagonal=int(diagonal))


def triu(x, diagonal=0):
    return _triu(x, diagonal=int(diagonal))


@register_op
def _diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, k=offset)


def diag(x, offset=0, padding_value=0):
    return _diag(x, offset=int(offset), padding_value=padding_value)


def diagflat(x, offset=0):
    return dispatch(lambda v: jnp.diagflat(v, k=int(offset)), (x,), {}, name="diagflat")


@register_op
def assign(x):
    """paddle.assign — copy (identity with new buffer semantics)."""
    return jnp.copy(x)


def clone(x):
    return assign(x)


def meshgrid(*args):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    return dispatch(lambda *vs: jnp.meshgrid(*vs, indexing="ij"), tuple(tensors), {},
                    name="meshgrid")


def numel(x) -> Tensor:
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def clone_detached(x):
    return x.detach()


def one_hot(x, num_classes) -> Tensor:
    return dispatch(lambda v: jax.nn.one_hot(v, int(num_classes),
                                             dtype=dtypes.get_default_dtype()),
                    (x,), {}, name="one_hot")


def complex(real, imag):
    return dispatch(lambda r, i: jax.lax.complex(r, i), (real, imag), {}, name="complex")


def polar(abs_t, angle_t):
    return dispatch(lambda a, t: jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t)),
                    (abs_t, angle_t), {}, name="polar")


def tril_indices(row, col, offset=0, dtype=None):
    r, c = np.tril_indices(int(row), int(offset), int(col))
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, dtypes.int64)))


def triu_indices(row, col=None, offset=0, dtype=None):
    col = row if col is None else col
    r, c = np.triu_indices(int(row), int(offset), int(col))
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, dtypes.int64)))


# --- random creation (paddle.tensor.random analog) --------------------------

def rand(shape, dtype=None) -> Tensor:
    d = _dt(dtype, dtypes.get_default_dtype())
    return Tensor(jax.random.uniform(_random.next_key(), _shape_tuple(shape), dtype=d))


def randn(shape, dtype=None) -> Tensor:
    d = _dt(dtype, dtypes.get_default_dtype())
    return Tensor(jax.random.normal(_random.next_key(), _shape_tuple(shape), dtype=d))


def standard_normal(shape, dtype=None) -> Tensor:
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        key = _random.next_key()
        return Tensor(m + s * jax.random.normal(key, shp, dtype=dtypes.get_default_dtype()))
    shp = _shape_tuple(shape if shape is not None else [1])
    key = _random.next_key()
    return Tensor(mean + std * jax.random.normal(key, shp, dtype=dtypes.get_default_dtype()))


def uniform(shape, dtype=None, min=-1.0, max=1.0) -> Tensor:
    d = _dt(dtype, dtypes.get_default_dtype())
    return Tensor(jax.random.uniform(_random.next_key(), _shape_tuple(shape), dtype=d,
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype=None) -> Tensor:
    if high is None:
        low, high = 0, low
    d = _dt(dtype, dtypes.int64)
    return Tensor(jax.random.randint(_random.next_key(), _shape_tuple(shape), low, high,
                                     dtype=d))


def randint_like(x, low=0, high=None, dtype=None) -> Tensor:
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def randperm(n, dtype=None) -> Tensor:
    d = _dt(dtype, dtypes.int64)
    return Tensor(jax.random.permutation(_random.next_key(), int(n)).astype(d))


def bernoulli(x) -> Tensor:
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(_random.next_key(), v).astype(v.dtype))


def poisson(x) -> Tensor:
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(_random.next_key(), v).astype(v.dtype))


def multinomial(x, num_samples=1, replacement=False) -> Tensor:
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    key = _random.next_key()
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(*v.shape[:-1], int(num_samples)))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, v.shape, dtype=jnp.float32)
        _, out = jax.lax.top_k(logits + g, int(num_samples))
    return Tensor(out.astype(jnp.int64))


def exponential_(x, lam=1.0):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    s = jax.random.exponential(_random.next_key(), v.shape, dtype=v.dtype) / lam
    if isinstance(x, Tensor):
        x._value = s
        return x
    return Tensor(s)
