"""Shape/layout/index manipulation ops (paddle.tensor.manipulation + search analog).

Reference: python/paddle/tensor/manipulation.py, search.py; view kernels in
paddle/phi/kernels/stride/ (as_strided, slice — zero-copy). Under XLA all reshapes/
slices are logical ops the compiler folds, so "stride kernels" need no analog.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, dispatch


def _ints(x):
    if isinstance(x, Tensor):
        x = x.tolist()
    if isinstance(x, (int, np.integer)):
        return int(x)
    return [int(v._value if isinstance(v, Tensor) else v) for v in x]


def cast(x, dtype):
    d = dtypes.convert_dtype(dtype)
    return dispatch(lambda v: v.astype(d), (x,), {}, name="cast")


astype = cast


def reshape(x, shape):
    shape = _ints(shape)
    return dispatch(lambda v: jnp.reshape(v, shape), (x,), {}, name="reshape")


def flatten(x, start_axis=0, stop_axis=-1):
    def fn(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new_shape)
    return dispatch(fn, (x,), {}, name="flatten")


def squeeze(x, axis=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        ax = _ints(axis)
        ax = [ax] if isinstance(ax, int) else ax
        ax = tuple(a % v.ndim for a in ax if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=ax) if ax else v
    return dispatch(fn, (x,), {}, name="squeeze")


def unsqueeze(x, axis):
    ax = _ints(axis)
    ax = [ax] if isinstance(ax, int) else ax
    return dispatch(lambda v: jnp.expand_dims(v, tuple(ax)), (x,), {}, name="unsqueeze")


def transpose(x, perm):
    perm = _ints(perm)
    return dispatch(lambda v: jnp.transpose(v, perm), (x,), {}, name="transpose")


def t(x):
    return dispatch(lambda v: v.T, (x,), {}, name="t")


def moveaxis(x, source, destination):
    return dispatch(lambda v: jnp.moveaxis(v, _ints(source), _ints(destination)),
                    (x,), {}, name="moveaxis")


def swapaxes(x, axis1, axis2):
    return dispatch(lambda v: jnp.swapaxes(v, int(axis1), int(axis2)), (x,), {},
                    name="swapaxes")


def concat(x, axis=0):
    tensors = tuple(x)
    ax = int(axis._value if isinstance(axis, Tensor) else axis)
    return dispatch(lambda *vs: jnp.concatenate(vs, axis=ax), tensors, {}, name="concat")


def stack(x, axis=0):
    tensors = tuple(x)
    return dispatch(lambda *vs: jnp.stack(vs, axis=int(axis)), tensors, {}, name="stack")


def split(x, num_or_sections, axis=0):
    ax = int(axis._value if isinstance(axis, Tensor) else axis)

    def fn(v):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=ax))
        secs = _ints(num_or_sections)
        total = v.shape[ax]
        known = builtins_sum(s for s in secs if s != -1)
        secs = [s if s != -1 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(v, idx, axis=ax))
    return list(dispatch(fn, (x,), {}, name="split"))


def chunk(x, chunks, axis=0):
    return split(x, int(chunks), axis)


def tensor_split(x, num_or_indices, axis=0):
    def fn(v):
        return tuple(jnp.array_split(v, num_or_indices if isinstance(num_or_indices, int)
                                     else _ints(num_or_indices), axis=int(axis)))
    return list(dispatch(fn, (x,), {}, name="tensor_split"))


def unbind(x, axis=0):
    def fn(v):
        return tuple(jnp.moveaxis(v, int(axis), 0))
    return list(dispatch(fn, (x,), {}, name="unbind"))


unstack = unbind


def tile(x, repeat_times):
    return dispatch(lambda v: jnp.tile(v, tuple(_ints(repeat_times))), (x,), {},
                    name="tile")


def expand(x, shape):
    shape = _ints(shape)

    def fn(v):
        tgt = list(shape)
        # paddle: -1 keeps the original dim
        off = len(tgt) - v.ndim
        for i in range(v.ndim):
            if tgt[off + i] == -1:
                tgt[off + i] = v.shape[i]
        return jnp.broadcast_to(v, tuple(tgt))
    return dispatch(fn, (x,), {}, name="expand")


def expand_as(x, y):
    return dispatch(lambda v, w: jnp.broadcast_to(v, w.shape), (x, y), {},
                    name="expand_as")


def broadcast_to(x, shape):
    return dispatch(lambda v: jnp.broadcast_to(v, tuple(_ints(shape))), (x,), {},
                    name="broadcast_to")


def broadcast_tensors(inputs):
    return list(dispatch(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), tuple(inputs), {},
                         name="broadcast_tensors"))


def flip(x, axis):
    ax = _ints(axis)
    ax = [ax] if isinstance(ax, int) else ax
    return dispatch(lambda v: jnp.flip(v, tuple(ax)), (x,), {}, name="flip")


def rot90(x, k=1, axes=(0, 1)):
    return dispatch(lambda v: jnp.rot90(v, k=int(k), axes=tuple(_ints(axes))), (x,), {},
                    name="rot90")


def roll(x, shifts, axis=None):
    def fn(v):
        ax = None if axis is None else _ints(axis)
        return jnp.roll(v, _ints(shifts), axis=tuple(ax) if isinstance(ax, list) else ax)
    return dispatch(fn, (x,), {}, name="roll")


def repeat_interleave(x, repeats, axis=None):
    def fn(v, r):
        return jnp.repeat(v, r, axis=None if axis is None else int(axis))
    return dispatch(fn, (x, repeats), {}, name="repeat_interleave")


def pad_nd(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    """Low-level jnp.pad wrapper; paddle.nn.functional.pad builds on this."""
    def fn(v):
        return jnp.pad(v, pad, mode=mode, constant_values=value) \
            if mode == "constant" else jnp.pad(v, pad, mode=mode)
    return dispatch(fn, (x,), {}, name="pad")


# -- indexing -----------------------------------------------------------------

def gather(x, index, axis=0):
    def fn(v, idx):
        return jnp.take(v, idx.reshape(-1) if idx.ndim > 1 else idx, axis=int(axis))
    return dispatch(fn, (x, index), {}, name="gather")


def gather_nd(x, index):
    def fn(v, idx):
        # idx [..., k] indexes the first k dims of v
        k = idx.shape[-1]
        out = v[tuple(jnp.moveaxis(idx, -1, 0))]
        return out
    return dispatch(fn, (x, index), {}, name="gather_nd")


def take_along_axis(x, indices, axis, broadcast=True):
    def fn(v, idx):
        if broadcast:
            tgt = list(v.shape)
            tgt[int(axis)] = idx.shape[int(axis)]
            idx = jnp.broadcast_to(idx, tuple(tgt))
        return jnp.take_along_axis(v, idx, axis=int(axis))
    return dispatch(fn, (x, indices), {}, name="take_along_axis")


def put_along_axis(x, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True):
    def fn(v, idx, val):
        if broadcast:
            tgt = list(v.shape)
            tgt[int(axis)] = idx.shape[int(axis)]
            idx = jnp.broadcast_to(idx, tuple(tgt))
        val = jnp.broadcast_to(jnp.asarray(val, v.dtype), idx.shape)
        dims = list(range(v.ndim))
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        full_idx = tuple(grids[d] if d != int(axis) % v.ndim else idx for d in dims)
        at = v.at[full_idx]
        if reduce == "assign":
            return at.set(val)
        if reduce == "add":
            return at.add(val)
        if reduce in ("mul", "multiply"):
            return at.multiply(val)
        if reduce == "amax":
            return at.max(val)
        if reduce == "amin":
            return at.min(val)
        raise ValueError(f"unknown reduce {reduce}")
    return dispatch(fn, (x, indices, values), {}, name="put_along_axis")


def index_select(x, index, axis=0):
    return dispatch(lambda v, i: jnp.take(v, i, axis=int(axis)), (x, index), {},
                    name="index_select")


def index_sample(x, index):
    def fn(v, idx):
        rows = jnp.arange(v.shape[0])[:, None]
        return v[rows, idx]
    return dispatch(fn, (x, index), {}, name="index_sample")


def index_add(x, index, axis, value):
    def fn(v, i, val):
        v_m = jnp.moveaxis(v, int(axis), 0)
        val_m = jnp.moveaxis(val, int(axis), 0)
        out = v_m.at[i].add(val_m.astype(v.dtype))
        return jnp.moveaxis(out, 0, int(axis))
    return dispatch(fn, (x, index, value), {}, name="index_add")


def index_put(x, indices, value, accumulate=False):
    if isinstance(indices, (Tensor, jnp.ndarray, np.ndarray)):
        # a single advanced index (torch/paddle accept the bare form);
        # tuple(tensor) would spin forever — jnp __getitem__ clamps
        # out-of-range rows instead of raising IndexError
        indices = (indices,)

    def fn(v, idx_tuple, val):
        at = v.at[tuple(idx_tuple)]
        return at.add(val) if accumulate else at.set(val)
    return dispatch(fn, (x, tuple(indices), value), {}, name="index_put")


def masked_select(x, mask):
    # dynamic-shape output: eager-only (not jittable) — same caveat as reference's
    # dynamic ops under CINN.
    v = x._value if isinstance(x, Tensor) else x
    m = mask._value if isinstance(mask, Tensor) else mask
    out = np.asarray(v)[np.asarray(m)]
    return dispatch(lambda _: jnp.asarray(out), (x,), {}, name="masked_select") \
        if False else Tensor(jnp.asarray(out))


def masked_fill(x, mask, value):
    return dispatch(lambda v, m, val: jnp.where(m, jnp.asarray(val, v.dtype), v),
                    (x, mask, value), {}, name="masked_fill")


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return dispatch(lambda c, a, b: jnp.where(c, a, b), (condition, x, y), {},
                    name="where")


def nonzero(x, as_tuple=False):
    v = x._value if isinstance(x, Tensor) else x
    nz = np.nonzero(np.asarray(v))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None], dtype=jnp.int64)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), dtype=jnp.int64))


def scatter(x, index, updates, overwrite=True):
    def fn(v, i, u):
        i = i.reshape(-1) if i.ndim > 1 else i
        if overwrite:
            return v.at[i].set(u.astype(v.dtype))
        # paddle: overwrite=False sums duplicates after zeroing target rows
        zeroed = v.at[i].set(jnp.zeros_like(u, v.dtype))
        return zeroed.at[i].add(u.astype(v.dtype))
    return dispatch(fn, (x, index, updates), {}, name="scatter")


def scatter_nd_add(x, index, updates):
    def fn(v, idx, u):
        return v.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u.astype(v.dtype))
    return dispatch(fn, (x, index, updates), {}, name="scatter_nd_add")


def scatter_nd(index, updates, shape):
    def fn(idx, u):
        z = jnp.zeros(tuple(_ints(shape)), u.dtype)
        return z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return dispatch(fn, (index, updates), {}, name="scatter_nd")


def slice(x, axes, starts, ends):
    axes_l, starts_l, ends_l = _ints(axes), _ints(starts), _ints(ends)

    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes_l, starts_l, ends_l):
            idx[a] = builtins_slice(s, e)
        return v[tuple(idx)]
    return dispatch(fn, (x,), {}, name="slice")


def strided_slice(x, axes, starts, ends, strides):
    axes_l, starts_l, ends_l, strides_l = map(_ints, (axes, starts, ends, strides))

    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e, st in zip(axes_l, starts_l, ends_l, strides_l):
            idx[a] = builtins_slice(s, e, st)
        return v[tuple(idx)]
    return dispatch(fn, (x,), {}, name="strided_slice")


def as_strided(x, shape, stride, offset=0):
    def fn(v):
        flat = v.reshape(-1)
        idx = jnp.full(tuple(_ints(shape)), int(offset))
        for d, (s, st) in enumerate(zip(_ints(shape), _ints(stride))):
            r = jnp.arange(s) * st
            br = r.reshape([-1 if i == d else 1 for i in range(len(_ints(shape)))])
            idx = idx + br
        return flat[idx]
    return dispatch(fn, (x,), {}, name="as_strided")


# -- search / sort ------------------------------------------------------------

def argmax(x, axis=None, keepdim=False, dtype="int64"):
    d = dtypes.convert_dtype(dtype)

    def fn(v):
        out = jnp.argmax(v, axis=None if axis is None else int(axis), keepdims=keepdim)
        return out.astype(d)
    return dispatch(fn, (x,), {}, name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    d = dtypes.convert_dtype(dtype)

    def fn(v):
        out = jnp.argmin(v, axis=None if axis is None else int(axis), keepdims=keepdim)
        return out.astype(d)
    return dispatch(fn, (x,), {}, name="argmin")


def argsort(x, axis=-1, descending=False, stable=True):
    def fn(v):
        out = jnp.argsort(v, axis=int(axis), stable=stable, descending=descending)
        return out.astype(jnp.int64)
    return dispatch(fn, (x,), {}, name="argsort")


def sort(x, axis=-1, descending=False, stable=True):
    def fn(v):
        out = jnp.sort(v, axis=int(axis), stable=stable, descending=descending)
        return out
    return dispatch(fn, (x,), {}, name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True):
    kk = int(k._value if isinstance(k, Tensor) else k)

    def fn(v):
        ax = int(axis) % v.ndim
        vv = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vv, kk)
        else:
            vals, idx = jax.lax.top_k(-vv, kk)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(jnp.int64))
    return dispatch(fn, (x,), {}, name="topk")


def kthvalue(x, k, axis=-1, keepdim=False):
    def fn(v):
        ax = int(axis) % v.ndim
        sv = jnp.sort(v, axis=ax)
        si = jnp.argsort(v, axis=ax, stable=True)
        vals = jnp.take(sv, int(k) - 1, axis=ax)
        idx = jnp.take(si, int(k) - 1, axis=ax).astype(jnp.int64)
        if keepdim:
            vals, idx = jnp.expand_dims(vals, ax), jnp.expand_dims(idx, ax)
        return vals, idx
    return dispatch(fn, (x,), {}, name="kthvalue")


def mode(x, axis=-1, keepdim=False):
    def fn(v):
        ax = int(axis) % v.ndim
        vm = jnp.moveaxis(v, ax, -1)
        # O(n^2) pairwise count along the axis — exact and jit-friendly
        counts = jnp.sum(vm[..., :, None] == vm[..., None, :], axis=-1)
        # prefer the largest value among equally-frequent candidates (paddle semantics)
        order = jnp.lexsort((vm, counts))  # ascending by count, then value
        best = order[..., -1:]
        vals = jnp.take_along_axis(vm, best, axis=-1)
        idx = best.astype(jnp.int64)
        vals = jnp.moveaxis(vals, -1, ax)
        idx = jnp.moveaxis(idx, -1, ax)
        if not keepdim:
            vals, idx = jnp.squeeze(vals, ax), jnp.squeeze(idx, ax)
        return vals, idx
    return dispatch(fn, (x,), {}, name="mode")


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    def fn(s, v):
        side = "right" if right else "left"
        if s.ndim == 1:
            out = jnp.searchsorted(s, v, side=side)
        else:
            out = jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(
                s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return dispatch(fn, (sorted_sequence, values), {}, name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32, right)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64"):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    if axis is not None:
        # slice-wise dedup along `axis` (reference: unique_consecutive_op —
        # consecutive equal SLICES collapse)
        ax = int(axis) % v.ndim
        moved = np.moveaxis(v, ax, 0)
        n = moved.shape[0]
        flat2 = moved.reshape(n, -1)
        keep = np.concatenate([[True],
                               np.any(flat2[1:] != flat2[:-1], axis=1)]) \
            if n > 0 else np.zeros(0, bool)
        uniq = np.moveaxis(moved[keep], 0, ax)
        out = [Tensor(jnp.asarray(uniq))]
        size = n
    else:
        flat = v.reshape(-1)
        keep = np.concatenate([[True], flat[1:] != flat[:-1]]) \
            if flat.size else np.zeros(0, bool)
        out = [Tensor(jnp.asarray(flat[keep]))]
        size = flat.size
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, size))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


def histogram(x, bins=100, min=0, max=0, weight=None, density=False):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    lo, hi = (float(min), float(max)) if (min != 0 or max != 0) else (v.min(), v.max())
    w = np.asarray(weight._value) if isinstance(weight, Tensor) else weight
    hist, _ = np.histogram(v, bins=int(bins), range=(lo, hi), weights=w, density=density)
    return Tensor(jnp.asarray(hist if density or w is not None else hist.astype(np.int64)))


def bincount(x, weights=None, minlength=0):
    def fn(v, w):
        length = builtins_max(int(minlength), int(np.asarray(v).max()) + 1 if v.size else 0)
        return jnp.bincount(v, weights=w, length=length)
    v = x._value if isinstance(x, Tensor) else x
    w = weights._value if isinstance(weights, Tensor) else weights
    return Tensor(fn(v, w))


def atleast_1d(*xs):
    outs = [dispatch(jnp.atleast_1d, (x,), {}, name="atleast_1d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs):
    outs = [dispatch(jnp.atleast_2d, (x,), {}, name="atleast_2d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs):
    outs = [dispatch(jnp.atleast_3d, (x,), {}, name="atleast_3d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def tensordot(x, y, axes=2):
    return dispatch(lambda a, b: jnp.tensordot(a, b, axes=axes), (x, y), {},
                    name="tensordot")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(v):
        shard_size = (int(index_num) + int(nshards) - 1) // int(nshards)
        lo = shard_id * shard_size
        hi = lo + shard_size
        in_shard = (v >= lo) & (v < hi)
        return jnp.where(in_shard, v - lo, ignore_value)
    return dispatch(fn, (input,), {}, name="shard_index")


import builtins
builtins_slice = builtins.slice
builtins_sum = builtins.sum
builtins_max = builtins.max
