"""Op library — the phi-kernel analog, one flat namespace.

Reference: paddle/phi/kernels (605 public kernels) exposed through
python/paddle/tensor/*. All ops are pure jax functions dispatched through the eager
tape (core/tensor.py); under jit they trace into the compiled program.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401

from .creation import assign, to_tensor  # noqa: F401
from .extras import *  # noqa: F401,F403
