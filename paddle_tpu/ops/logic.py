"""Comparison / logical / bitwise ops (paddle.tensor.logic analog).

Reference: python/paddle/tensor/logic.py → phi compare/logical/bitwise kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch


def _binary(name, fn):
    def op(x, y, name_arg=None):
        return dispatch(fn, (x, y), {}, name=name)
    op.__name__ = name
    return op


equal = _binary("equal", jnp.equal)
not_equal = _binary("not_equal", jnp.not_equal)
greater_than = _binary("greater_than", jnp.greater)
greater_equal = _binary("greater_equal", jnp.greater_equal)
less_than = _binary("less_than", jnp.less)
less_equal = _binary("less_equal", jnp.less_equal)
logical_and = _binary("logical_and", jnp.logical_and)
logical_or = _binary("logical_or", jnp.logical_or)
logical_xor = _binary("logical_xor", jnp.logical_xor)
bitwise_and = _binary("bitwise_and", jnp.bitwise_and)
bitwise_or = _binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binary("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _binary("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _binary("bitwise_right_shift", jnp.right_shift)


def logical_not(x):
    return dispatch(jnp.logical_not, (x,), {}, name="logical_not")


def bitwise_not(x):
    return dispatch(jnp.bitwise_not, (x,), {}, name="bitwise_not")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return dispatch(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                             equal_nan=equal_nan),
                    (x, y), {}, name="isclose")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return dispatch(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                              equal_nan=equal_nan),
                    (x, y), {}, name="allclose")


def equal_all(x, y):
    return dispatch(lambda a, b: jnp.array_equal(a, b), (x, y), {}, name="equal_all")


def is_empty(x):
    v = x._value if isinstance(x, Tensor) else x
    return Tensor(jnp.asarray(v.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
