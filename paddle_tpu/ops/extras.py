"""Long-tail tensor ops closing the top-level API gap vs the reference
(python/paddle/__init__.py __all__). Everything lowers to jnp/lax through
dispatch; host-side combinatorics (combinations, vander sizes) stay static.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..core import random as _random
from .creation import to_tensor

__all__ = [
    "sinc", "signbit", "isin", "isneginf", "isposinf", "isreal", "is_complex",
    "is_integer", "is_floating_point", "cdist", "pdist", "histogram_bin_edges",
    "histogramdd", "frexp", "trapezoid", "cumulative_trapezoid",
    "vander", "polygamma", "gammaln", "gammainc", "gammaincc", "multigammaln",
    "take", "combinations", "block_diag", "logit", "slice_scatter",
    "select_scatter", "diagonal_scatter", "renorm", "sgn", "log_normal",
    "standard_gamma", "binomial", "vecdot", "unflatten", "view", "view_as",
    "unfold", "crop", "multiplex", "reduce_as", "broadcast_shape", "hsplit",
    "vsplit", "dsplit", "hstack", "vstack", "dstack", "column_stack",
    "row_stack", "bitwise_invert", "less", "negative", "positive",
    "matrix_transpose", "index_fill", "masked_scatter", "cartesian_prod",
    "reverse", "cauchy_", "geometric_", "log_normal_", "bernoulli_", "normal_",
]


def _u(jfn, op_name):
    def op(x, name=None):
        return dispatch(lambda v: jfn(v), (x,), {}, name=op_name)

    op.__name__ = op_name
    return op


sinc = _u(jnp.sinc, "sinc")
signbit = _u(jnp.signbit, "signbit")
isneginf = _u(jnp.isneginf, "isneginf")
isposinf = _u(jnp.isposinf, "isposinf")
isreal = _u(jnp.isreal, "isreal")
negative = _u(jnp.negative, "negative")
positive = _u(lambda v: v, "positive")
gammaln = _u(jax.scipy.special.gammaln, "gammaln")


def is_complex(x):
    return jnp.issubdtype(jnp.asarray(x._value).dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x._value).dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x._value).dtype, jnp.floating)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    def fn(a, t):
        return jnp.isin(a, t, invert=invert)

    return dispatch(fn, (x, test_x), {}, name="isin")


def _safe_sqrt(s):
    # double-where keeps the backward pass NaN-free at s == 0 (the gradient
    # there is 0, matching torch.cdist's subgradient convention)
    pos = s > 0
    return jnp.where(pos, jnp.sqrt(jnp.where(pos, s, 1.0)), 0.0)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-norm distances (reference: tensor/linalg.py cdist)."""
    def fn(a, b):
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 2.0:
            return _safe_sqrt(jnp.sum(diff * diff, -1))
        if p == float("inf"):
            return jnp.max(diff, -1)
        return jnp.power(jnp.sum(jnp.power(diff, p), -1), 1.0 / p)

    return dispatch(fn, (x, y), {}, name="cdist")


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of one point set."""
    n = x.shape[-2]
    iu = np.triu_indices(n, k=1)

    def fn(a):
        full = jnp.abs(a[..., :, None, :] - a[..., None, :, :])
        if p == 2.0:
            d = _safe_sqrt(jnp.sum(full * full, -1))
        elif p == float("inf"):
            d = jnp.max(full, -1)
        else:
            d = jnp.power(jnp.sum(jnp.power(full, p), -1), 1.0 / p)
        return d[..., iu[0], iu[1]]

    return dispatch(fn, (x,), {}, name="pdist")


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    if max < min:
        raise ValueError(f"max ({max}) must be >= min ({min})")

    def fn(v):
        if min == 0 and max == 0:
            lo, hi = jnp.min(v), jnp.max(v)
        else:
            lo, hi = jnp.asarray(min, v.dtype), jnp.asarray(max, v.dtype)
        # degenerate range widens by ±0.5 (reference histogram semantics)
        same = lo == hi
        lo = jnp.where(same, lo - 0.5, lo)
        hi = jnp.where(same, hi + 0.5, hi)
        return jnp.linspace(lo, hi, bins + 1)

    return dispatch(fn, (x,), {}, name="histogram_bin_edges")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    xv = np.asarray(x._value)
    wv = None if weights is None else np.asarray(weights._value)
    if ranges is not None:
        # paddle's `ranges` is a FLAT list of 2*D floats; numpy wants one
        # (lo, hi) pair per dimension
        flat = list(ranges)
        if len(flat) != 2 * xv.shape[-1]:
            raise ValueError(
                f"ranges must hold 2 floats per dimension "
                f"({2 * xv.shape[-1]}), got {len(flat)}")
        ranges = [(flat[2 * i], flat[2 * i + 1])
                  for i in range(xv.shape[-1])]
    hist, edges = np.histogramdd(xv, bins=bins, range=ranges, density=density,
                                 weights=wv)
    return to_tensor(hist), [to_tensor(e) for e in edges]


def frexp(x, name=None):
    def fn(v):
        m, e = jnp.frexp(v)
        return m, e.astype(v.dtype)  # reference returns exponent in x's dtype

    return dispatch(fn, (x,), {}, name="frexp")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yv, xv):
        return jnp.trapezoid(yv, x=xv, dx=dx if dx is not None else 1.0,
                             axis=axis)

    return dispatch(fn, (y, x), {}, name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Differentiable/jittable (cumsum of pairwise trapezoid areas) — the
    scipy host path would carry no tape and break to_static."""
    step = 1.0 if dx is None else float(dx)

    def _sl(v, sl, ax):
        idx = [slice(None)] * v.ndim
        idx[ax] = sl
        return v[tuple(idx)]

    if x is not None:
        def fn(yv, xv):
            ax = axis % yv.ndim
            if xv.ndim == yv.ndim:
                d = jnp.diff(xv.astype(yv.dtype), axis=ax)
            else:
                # 1-D sample points apply along `ax`: reshape so the
                # broadcast lands on that axis, not the trailing one
                d = jnp.diff(xv.astype(yv.dtype)).reshape(
                    [-1 if i == ax else 1 for i in range(yv.ndim)])
            pair = (_sl(yv, slice(1, None), ax)
                    + _sl(yv, slice(None, -1), ax)) / 2
            return jnp.cumsum(pair * d, axis=ax)
        return dispatch(fn, (y, x), {}, name="cumulative_trapezoid")

    def fn(yv):
        ax = axis % yv.ndim
        pair = (_sl(yv, slice(1, None), ax)
                + _sl(yv, slice(None, -1), ax)) / 2
        return jnp.cumsum(pair * jnp.asarray(step, yv.dtype), axis=ax)
    return dispatch(fn, (y,), {}, name="cumulative_trapezoid")


def vander(x, n=None, increasing=False, name=None):
    cols = n if n is not None else int(x.shape[0])

    def fn(v):
        return jnp.vander(v, N=cols, increasing=increasing)

    return dispatch(fn, (x,), {}, name="vander")


def polygamma(x, n, name=None):
    def fn(v):
        return jax.scipy.special.polygamma(n, v)

    return dispatch(fn, (x,), {}, name="polygamma")


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (paddle arg order)."""
    def fn(a, b):
        return jax.scipy.special.gammainc(a, b)

    return dispatch(fn, (x, y), {}, name="gammainc")


def gammaincc(x, y, name=None):
    def fn(a, b):
        return jax.scipy.special.gammaincc(a, b)

    return dispatch(fn, (x, y), {}, name="gammaincc")


def multigammaln(x, p, name=None):
    def fn(v):
        js = jnp.arange(1, p + 1, dtype=v.dtype)
        return (p * (p - 1) / 4.0) * math.log(math.pi) + jnp.sum(
            jax.scipy.special.gammaln(v[..., None] + (1.0 - js) / 2.0), -1)

    return dispatch(fn, (x,), {}, name="multigammaln")


def take(x, index, mode="raise", name=None):
    def fn(v, idx):
        flat = v.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx = idx % n
        elif mode == "clip":
            # reference: clip mode disables negative indexing entirely
            idx = jnp.clip(idx, 0, n - 1)
        return flat[idx]

    return dispatch(fn, (x, index), {}, name="take")


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    n = int(x.shape[0])
    combos = list(itertools.combinations_with_replacement(range(n), r)
                  if with_replacement else itertools.combinations(range(n), r))
    idx = jnp.asarray(np.asarray(combos, dtype=np.int64).reshape(-1, r)
                      if combos else np.zeros((0, r), np.int64))

    def fn(v):
        return v[idx]

    return dispatch(fn, (x,), {}, name="combinations")


def block_diag(inputs, name=None):
    def fn(*vals):
        return jax.scipy.linalg.block_diag(*vals)

    return dispatch(lambda *v: fn(*v), tuple(inputs), {}, name="block_diag")


def logit(x, eps=None, name=None):
    def fn(v):
        z = v if eps is None else jnp.clip(v, eps, 1 - eps)
        out = jnp.log(z) - jnp.log1p(-z)
        if eps is None:
            out = jnp.where((v < 0) | (v > 1), jnp.nan, out)
        return out

    return dispatch(fn, (x,), {}, name="logit")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(v, val):
        idx = [slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sd)
        return v.at[tuple(idx)].set(val)

    return dispatch(fn, (x, value), {}, name="slice_scatter")


def select_scatter(x, values, axis, index, name=None):
    def fn(v, val):
        idx = [slice(None)] * v.ndim
        idx[axis] = index
        return v.at[tuple(idx)].set(val)

    return dispatch(fn, (x, values), {}, name="select_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def fn(v, val):
        # route the diagonal to the last two axes, scatter, route back
        perm = [d for d in range(v.ndim) if d not in (axis1 % v.ndim,
                                                      axis2 % v.ndim)]
        perm += [axis1 % v.ndim, axis2 % v.ndim]
        inv = np.argsort(perm)
        vp = jnp.transpose(v, perm)
        n, m = vp.shape[-2], vp.shape[-1]
        rows = jnp.arange(max(n, m))
        if offset >= 0:
            r, c = rows[: min(n, m - offset)], rows[: min(n, m - offset)] + offset
        else:
            r, c = rows[: min(n + offset, m)] - offset, rows[: min(n + offset, m)]
        vp = vp.at[..., r, c].set(val)
        return jnp.transpose(vp, inv)

    return dispatch(fn, (x, y), {}, name="diagonal_scatter")


def renorm(x, p, axis, max_norm, name=None):
    def fn(v):
        axes = tuple(d for d in range(v.ndim) if d != axis % v.ndim)
        norms = jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=axes,
                                  keepdims=True), 1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor

    return dispatch(fn, (x,), {}, name="renorm")


def sgn(x, name=None):
    def fn(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.maximum(mag, 1e-38))
        return jnp.sign(v)

    return dispatch(fn, (x,), {}, name="sgn")


def vecdot(x, y, axis=-1, name=None):
    def fn(a, b):
        return jnp.sum(a * b, axis=axis)

    return dispatch(fn, (x, y), {}, name="vecdot")


def unflatten(x, axis, shape, name=None):
    def fn(v):
        new_shape = list(v.shape)
        ax = axis % v.ndim
        new_shape[ax:ax + 1] = list(shape)
        return v.reshape(new_shape)

    return dispatch(fn, (x,), {}, name="unflatten")


def view(x, shape_or_dtype, name=None):
    from ..core.dtype import convert_dtype
    if isinstance(shape_or_dtype, (list, tuple)):
        def fn(v):
            return v.reshape([int(s) for s in shape_or_dtype])
        return dispatch(fn, (x,), {}, name="view")

    dt = convert_dtype(shape_or_dtype)

    def fn(v):
        out = jax.lax.bitcast_convert_type(v, dt)
        # fold the reinterpretation into the LAST dim (reference view
        # semantics: [.., D] fp32 -> [.., 4D] uint8, fp32 pairs -> fp64 halves)
        if out.ndim == v.ndim + 1:          # narrowing appended a dim
            return out.reshape(v.shape[:-1] + (v.shape[-1] * out.shape[-1],))
        if out.ndim == v.ndim - 1:          # widening consumed the last dim
            return out
        return out

    if dt.itemsize > np.dtype(x._value.dtype).itemsize:
        ratio = dt.itemsize // np.dtype(x._value.dtype).itemsize
        if int(x.shape[-1]) % ratio:
            raise ValueError(
                f"view to wider dtype needs last dim divisible by {ratio}")

        def fn(v):
            grouped = v.reshape(v.shape[:-1] + (v.shape[-1] // ratio, ratio))
            return jax.lax.bitcast_convert_type(grouped, dt).reshape(
                v.shape[:-1] + (v.shape[-1] // ratio,))

    return dispatch(fn, (x,), {}, name="view")


def view_as(x, other, name=None):
    return view(x, other.shape)


def unfold(x, axis, size, step, name=None):
    """Sliding windows along an axis (reference: tensor/manipulation.py
    unfold — the torch.Tensor.unfold analog)."""
    n = int(x.shape[axis])
    num = (n - size) // step + 1
    starts = np.arange(num) * step
    idx = starts[:, None] + np.arange(size)[None, :]
    jidx = jnp.asarray(idx)

    def fn(v):
        out = jnp.take(v, jidx.reshape(-1), axis=axis)
        ax = axis % v.ndim
        new_shape = list(v.shape)
        new_shape[ax:ax + 1] = [num, size]
        out = out.reshape(new_shape)
        # windows dim goes where the axis was; window content to the end
        return jnp.moveaxis(out, ax + 1, -1)

    return dispatch(fn, (x,), {}, name="unfold")


def crop(x, shape=None, offsets=None, name=None):
    def fn(v):
        offs = offsets or [0] * v.ndim
        shp = [v.shape[i] - offs[i] if s in (-1, None) else s
               for i, s in enumerate(shape or list(v.shape))]
        idx = tuple(slice(o, o + s) for o, s in zip(offs, shp))
        return v[idx]

    return dispatch(fn, (x,), {}, name="crop")


def multiplex(inputs, index, name=None):
    def fn(idx, *vals):
        stacked = jnp.stack(vals)                      # [K, B, ...]
        rows = idx.reshape(-1).astype(jnp.int32)
        return stacked[rows, jnp.arange(stacked.shape[1])]

    return dispatch(lambda idx, *v: fn(idx, *v), (index,) + tuple(inputs), {},
                    name="multiplex")


def reduce_as(x, target, name=None):
    def fn(v, t):
        extra = v.ndim - t.ndim
        axes = tuple(range(extra)) + tuple(
            extra + i for i in range(t.ndim) if t.shape[i] == 1 and
            v.shape[extra + i] != 1)
        out = jnp.sum(v, axis=axes, keepdims=False)
        return out.reshape(t.shape)

    return dispatch(fn, (x, target), {}, name="reduce_as")


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def _split_like(np_like, op_name):
    def op(x, num_or_indices, name=None):
        def fn(v):
            return tuple(np_like(v, num_or_indices))

        return dispatch(fn, (x,), {}, name=op_name)

    op.__name__ = op_name
    return op


hsplit = _split_like(jnp.hsplit, "hsplit")
vsplit = _split_like(jnp.vsplit, "vsplit")
dsplit = _split_like(jnp.dsplit, "dsplit")


def _stack_like(jfn, op_name):
    def op(x, name=None):
        return dispatch(lambda *v: jfn(v), tuple(x), {}, name=op_name)

    op.__name__ = op_name
    return op


hstack = _stack_like(jnp.hstack, "hstack")
vstack = _stack_like(jnp.vstack, "vstack")
dstack = _stack_like(jnp.dstack, "dstack")
column_stack = _stack_like(jnp.column_stack, "column_stack")
row_stack = vstack


def bitwise_invert(x, out=None, name=None):
    return dispatch(lambda v: jnp.invert(v), (x,), {}, name="bitwise_invert")


def less(x, y, name=None):
    def fn(a, b):
        return a < b

    return dispatch(fn, (x, y), {}, name="less")


def matrix_transpose(x, name=None):
    return dispatch(lambda v: jnp.swapaxes(v, -1, -2), (x,), {},
                    name="matrix_transpose")


def index_fill(x, index, axis, value, name=None):
    def fn(v, idx):
        sl = [slice(None)] * v.ndim
        sl[axis % v.ndim] = idx
        return v.at[tuple(sl)].set(value)

    return dispatch(fn, (x, index), {}, name="index_fill")


def masked_scatter(x, mask, value, name=None):
    mv = np.asarray(mask._value, dtype=bool)
    n = int(mv.sum())
    if int(np.prod(value.shape)) < n:
        raise ValueError(
            f"masked_scatter: value has {int(np.prod(value.shape))} elements "
            f"but the mask selects {n}")
    # static gather plan from the (host-resident) mask
    order = jnp.asarray(np.cumsum(mv.reshape(-1)) - 1)
    jm = jnp.asarray(mv)

    def fn(v, val):
        flat = v.reshape(-1)
        picked = val.reshape(-1)[order]
        return jnp.where(jm.reshape(-1), picked, flat).reshape(v.shape)

    return dispatch(fn, (x, value), {}, name="masked_scatter")


def cartesian_prod(x, name=None):
    def fn(*vals):
        grids = jnp.meshgrid(*vals, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    return dispatch(lambda *v: fn(*v), tuple(x), {}, name="cartesian_prod")


def reverse(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return dispatch(lambda v: jnp.flip(v, axes), (x,), {}, name="reverse")


# -- in-place random fills (reference: tensor/random.py *_ methods) ---------

def _inplace_random(fill_name):
    def op(x, *args, **kwargs):
        key = _random.next_key()
        v = jnp.asarray(x._value)
        if fill_name == "cauchy":
            loc = kwargs.get("loc", args[0] if args else 0.0)
            scale = kwargs.get("scale", args[1] if len(args) > 1 else 1.0)
            u = jax.random.uniform(key, v.shape, jnp.float32, 1e-6, 1 - 1e-6)
            out = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
        elif fill_name == "geometric":
            # reference fills the CONTINUOUS value log(u)/log1p(-p)
            # (tensor/creation.py geometric_), not torch's floored variant
            p = kwargs.get("probs", args[0] if args else 0.5)
            u = jax.random.uniform(key, v.shape, jnp.float32, 1e-6, 1 - 1e-6)
            out = jnp.log(u) / jnp.log1p(-p)
        elif fill_name == "log_normal":
            mean = kwargs.get("mean", args[0] if args else 1.0)
            std = kwargs.get("std", args[1] if len(args) > 1 else 2.0)
            out = jnp.exp(mean + std * jax.random.normal(key, v.shape))
        elif fill_name == "bernoulli":
            p = kwargs.get("p", args[0] if args else 0.5)
            out = jax.random.bernoulli(key, p, v.shape)
        else:  # normal
            mean = kwargs.get("mean", args[0] if args else 0.0)
            std = kwargs.get("std", args[1] if len(args) > 1 else 1.0)
            out = mean + std * jax.random.normal(key, v.shape)
        x._value = out.astype(v.dtype)
        return x

    op.__name__ = fill_name + "_"
    return op


cauchy_ = _inplace_random("cauchy")
geometric_ = _inplace_random("geometric")
log_normal_ = _inplace_random("log_normal")
bernoulli_ = _inplace_random("bernoulli")
normal_ = _inplace_random("normal")


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    key = _random.next_key()
    out = jnp.exp(mean + std * jax.random.normal(key, tuple(shape or ())))
    return to_tensor(out)


def standard_gamma(x, name=None):
    key = _random.next_key()

    def fn(v):
        return jax.random.gamma(key, v)

    out = dispatch(fn, (x,), {}, name="standard_gamma")
    out.stop_gradient = True
    return out


def binomial(count, prob, name=None):
    key = _random.next_key()

    def fn(n, p):
        return jax.random.binomial(key, n, p).astype(jnp.int64)

    out = dispatch(fn, (count, prob), {}, name="binomial")
    out.stop_gradient = True
    return out


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    def fn(v):
        return jnp.nanquantile(v, q, axis=axis, keepdims=keepdim)

    return dispatch(fn, (x,), {}, name="nanquantile")


def as_complex(x, name=None):
    """(..., 2) real pairs -> complex (reference: tensor/manipulation.py)."""
    def fn(v):
        return jax.lax.complex(v[..., 0], v[..., 1])

    return dispatch(fn, (x,), {}, name="as_complex")


def as_real(x, name=None):
    def fn(v):
        return jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1)

    return dispatch(fn, (x,), {}, name="as_real")


__all__ += ["nanquantile", "as_complex", "as_real"]
