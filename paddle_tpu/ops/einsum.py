"""einsum (paddle.einsum analog — reference: python/paddle/tensor/einsum.py).

Lowers directly to jnp.einsum: XLA maps contractions onto the MXU, which supersedes the
reference's hand-rolled plan builder + matmul decomposition.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import dispatch


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return dispatch(lambda *vs: jnp.einsum(equation, *vs), operands, {}, name="einsum")
