"""TensorArray ops (reference: python/paddle/tensor/array.py).

The reference's dygraph TensorArray IS a python list (array.py:71 asserts
``isinstance(array, list)`` in dynamic mode); the DENSE_TENSOR_ARRAY variable
only exists for the legacy static graph. TPU-native mapping:

- eager / concrete index: plain list semantics, bit-for-bit the reference's
  dygraph behavior (append at i == len, overwrite at i < len).
- traced dynamic index (inside jit/to_static): a list of same-shaped traced
  tensors reads via stack + ``lax.dynamic_index_in_dim`` — the
  compiler-friendly form of the static TensorArray read (no host sync, no
  data-dependent python).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype

__all__ = ["array_length", "array_read", "array_write", "create_array"]


def create_array(dtype="float32", initialized_list=None):
    """A TensorArray: in dygraph, a python list (reference array.py:309)."""
    arr = []
    if initialized_list is not None:
        for x in initialized_list:
            if not isinstance(x, Tensor):
                x = Tensor(jnp.asarray(x, convert_dtype(dtype)))
            arr.append(x)
    return arr


def array_length(array):
    """Length of the array as a 0-D int64 Tensor (reference array.py:43)."""
    if not isinstance(array, list):
        raise TypeError("array_length expects a list (dygraph TensorArray)")
    return Tensor(jnp.asarray(len(array), jnp.int64))


def _index(i):
    v = i._value if isinstance(i, Tensor) else i
    if isinstance(v, jax.core.Tracer):
        return v, True
    return int(jnp.reshape(v, ()) if hasattr(v, "shape") else v), False


def array_read(array, i):
    """array[i] (reference array.py:110). A TRACED index lowers to
    stack + dynamic_index_in_dim so reads stay inside the compiled program."""
    if not isinstance(array, list):
        raise TypeError("array_read expects a list (dygraph TensorArray)")
    idx, traced = _index(i)
    if not traced:
        return array[idx]
    from ..core.tensor import dispatch

    def fn(iv, *vals):
        stacked = jnp.stack(vals)
        return jax.lax.dynamic_index_in_dim(
            stacked, jnp.reshape(iv, ()).astype(jnp.int32), 0,
            keepdims=False)

    return dispatch(fn, (i, *array), {}, name="array_read")


def array_write(x, i, array=None):
    """Write ``x`` at position ``i`` (append when i == len). Returns the
    array (reference array.py:206)."""
    if array is None:
        array = []
    if not isinstance(array, list):
        raise TypeError("array_write expects a list (dygraph TensorArray)")
    idx, traced = _index(i)
    if traced:
        raise ValueError(
            "array_write with a traced index is data-dependent list "
            "mutation — hoist the write out of the compiled region or use "
            "a concrete index (the reference's dygraph mode has the same "
            "host-index contract, array.py:258)")
    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    if idx > len(array):
        raise IndexError(
            f"array_write index {idx} out of range (len {len(array)})")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array
