"""paddle.onnx analog — ONNX export (gated).

Reference: python/paddle/onnx/export.py (delegates to the external paddle2onnx
converter). This environment has no ONNX toolchain; the TPU-native deployment
path is paddle_tpu.static.save_inference_model (serialized StableHLO via
jax.export) + paddle_tpu.inference.Predictor. export() raises with that
guidance unless the `onnx` package is importable.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise RuntimeError(
            "ONNX export needs the `onnx` package, which is not available in "
            "this environment. Use paddle_tpu.static.save_inference_model "
            "(StableHLO via jax.export) + paddle_tpu.inference.Predictor for "
            "deployment.") from None
    raise NotImplementedError(
        "onnx conversion from jaxpr is not implemented; use "
        "paddle_tpu.static.save_inference_model for deployment")
