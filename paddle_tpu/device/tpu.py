"""TPU device queries — the native citizen the reference kept for XPU/custom
devices (python/paddle/device/xpu/, device/__init__.py custom-device APIs)."""
from __future__ import annotations

import jax


def device_count():
    return len([d for d in jax.devices() if d.platform in ("tpu", "axon")])


def devices():
    return [d for d in jax.devices() if d.platform in ("tpu", "axon")]


def memory_stats(device=None):
    d = device or (devices()[0] if devices() else jax.devices()[0])
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def synchronize(device=None):
    for d in ([device] if device else devices()):
        try:
            d.synchronize_all_activity()
        except Exception:
            pass
