"""paddle.device.cuda parity surface mapped onto the PJRT accelerator
(reference: python/paddle/device/cuda/__init__.py). On TPU builds, "cuda"
queries report the TPU accelerator — same trick the reference uses for
CUDAPlace-on-XPU compatibility shims."""
from __future__ import annotations

import jax


def _accel():
    for d in jax.devices():
        if d.platform != "cpu":
            return d
    return jax.devices()[0]


def device_count():
    return len([d for d in jax.devices() if d.platform != "cpu"])


def _stats(device=None):
    d = _accel() if device is None else device
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def max_memory_allocated(device=None):
    return int(_stats(device).get("peak_bytes_in_use", 0))


def max_memory_reserved(device=None):
    s = _stats(device)
    return int(s.get("peak_pool_bytes", s.get("peak_bytes_in_use", 0)))


def memory_allocated(device=None):
    return int(_stats(device).get("bytes_in_use", 0))


def memory_reserved(device=None):
    s = _stats(device)
    return int(s.get("pool_bytes", s.get("bytes_in_use", 0)))


def empty_cache():
    pass  # PJRT owns the allocator


def synchronize(device=None):
    d = _accel() if device is None else device
    try:
        d.synchronize_all_activity()
    except Exception:
        pass


def get_device_properties(device=None):
    d = _accel() if device is None else device

    class _Props:
        name = d.device_kind
        major = 0
        minor = 0
        total_memory = int(_stats(d).get("bytes_limit", 0))
        multi_processor_count = getattr(d, "core_count", 1) or 1

    return _Props()


def get_device_name(device=None):
    return (_accel() if device is None else device).device_kind


def get_device_capability(device=None):
    return (0, 0)
