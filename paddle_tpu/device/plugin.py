"""CustomRuntime device plugins (reference: paddle/phi/backends/device_ext.h
— the C ABI third-party accelerators fill with function pointers, registered
through CustomRuntime/custom_device).

TPU-native mapping: the PJRT plugin interface IS the XLA world's
device-plugin ABI. A vendor ships a ``libpjrt_<name>.so`` implementing the
PJRT C API; registering it here makes the platform visible to the runtime
(``jax.devices()``, ``paddle.set_device``) exactly like the reference's
CustomPlace devices. No framework recompilation, same plug-in contract.
"""
from __future__ import annotations

import os

_REGISTERED: dict[str, str] = {}


def register_custom_runtime(name: str, library_path: str, options=None):
    """Register a PJRT plugin as a custom device runtime.

    name: platform name (becomes the device type, e.g. ``set_device(name)``).
    library_path: path to the plugin's PJRT C-API shared library.
    options: optional dict of plugin creation options.

    Must be called before the backend initializes (first device use) —
    the same constraint the reference's CustomRuntime registration has
    (plugins load at phi backend init).
    """
    if not isinstance(name, str) or not name:
        raise ValueError("custom runtime name must be a non-empty string")
    if name in ("cpu", "tpu", "gpu", "cuda"):
        raise ValueError(f"{name!r} is a built-in platform, not a plugin")
    if not os.path.exists(library_path):
        raise FileNotFoundError(
            f"CustomRuntime plugin library not found: {library_path}")
    from jax._src import xla_bridge
    if hasattr(xla_bridge, "backends_are_initialized") \
            and xla_bridge.backends_are_initialized():
        raise RuntimeError(
            "register_custom_runtime must run before the first device use "
            "(the PJRT backend set is fixed at initialization)")
    if not hasattr(xla_bridge, "register_plugin"):
        raise RuntimeError(
            "jax._src.xla_bridge.register_plugin is unavailable in this jax "
            "version — CustomRuntime plugin registration needs the PJRT "
            "plugin API (jax>=0.4.16); upgrade jax or load the plugin via "
            "the PJRT_NAMES_AND_LIBRARY_PATHS env var")
    xla_bridge.register_plugin(name, library_path=library_path,
                               options=options)
    _REGISTERED[name] = library_path
    return name


def list_custom_runtimes() -> dict:
    """Plugins registered through :func:`register_custom_runtime`."""
    return dict(_REGISTERED)
