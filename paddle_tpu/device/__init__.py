"""paddle.device analog (reference: python/paddle/device/ — set_device,
device queries, cuda.* memory stats, streams/events, Stream synchronize).

TPU-native: devices are PJRT devices; memory stats come from
jax Device.memory_stats(); streams are XLA-managed, so stream/event APIs are
ordering no-ops that exist for parity (everything on one device is already
program-ordered by XLA)."""
from __future__ import annotations

import jax

from ..core.device import (  # noqa: F401
    Place, CPUPlace, TPUPlace, CUDAPlace, XPUPlace, CustomPlace,
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
)
from . import cuda  # noqa: F401
from . import tpu  # noqa: F401


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [p for p in get_all_device_type() if p not in ("cpu", "tpu", "gpu")]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device()
            if d.split(":")[0] not in ("cpu", "tpu", "gpu")]


def device_count():
    return len(jax.devices())


def is_compiled_with_distribute():
    return True


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type=None):
    return any(d.platform not in ("cpu", "tpu", "gpu") for d in jax.devices())


def synchronize(device=None):
    """Block until all queued work on the device finished."""
    for d in jax.devices():
        try:
            d.synchronize_all_activity()
        except Exception:
            pass


class Stream:
    """Parity shim: XLA orders all work on a device; streams are implicit."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None): ...
    def query(self):
        return True

    def synchronize(self): ...


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


def get_cudnn_version():
    """reference: device/__init__.py get_cudnn_version — None when not built
    with CUDA (TPU build)."""
    return None


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    """XLA plays CINN's role (SURVEY §2.8); the CINN-specific API reports
    not-compiled like a standard wheel."""
    return False


def IPUPlace():
    raise RuntimeError("Can not use IPUPlace since PaddlePaddle is not "
                       "compiled with IPU")





from .plugin import register_custom_runtime, list_custom_runtimes  # noqa: F401,E402
