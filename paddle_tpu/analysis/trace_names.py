"""PTL008 — distributed-tracing strict-name pass.

The tracing layer (PR 19) added four more dynamic-string name spaces on
top of PTL005/PTL007's telemetry and SLO registries: request-timeline
event kinds (``FlightRecorder.req_event``), trace-hop ``via`` labels
(``TraceContext.mint``/``.child`` and the router's ``_bump_trace``),
Perfetto counter-track / flow-event names, and the tail-cause verdicts
``explain_tail`` may emit. All of them are joined BY STRING at read
time — ``explain_tail(...)['cause']``, a Perfetto query on a track
name, a dashboard grouping by hop ``via`` — so a typo'd literal never
crashes; it silently forks the vocabulary and the join quietly returns
nothing. This pass moves the whole vocabulary to lint time:

* every literal second argument of ``.req_event(rid, "...")`` must be
  in ``paddle_tpu/profiler/flight_recorder.py``'s
  ``REQUEST_EVENT_KINDS``;
* every literal hop label — ``.mint("...")``, ``.child("...")``, the
  trailing literal of ``._bump_trace(handle, "...")`` — must be in
  ``paddle_tpu/serving/types.py``'s ``TRACE_HOP_KINDS``;
* every Perfetto counter event (a dict literal with ``"ph": "C"``)
  whose ``"name"`` is a literal must name a ``COUNTER_TRACKS`` entry,
  and every flow event (``"ph": "s"``/``"f"``) with a literal name
  must use ``FLOW_EVENT_NAME``;
* every cause literal a producer writes — ``cause = "..."`` /
  ``...["cause"] = "..."`` assignments and ``return "..."`` inside a
  ``*classify*`` function — must be in ``TAIL_CAUSES`` or the router's
  ``FLEET_TAIL_CAUSES``;
* ``FLEET_TAIL_CAUSES`` itself must stay in lockstep with
  ``kv_transport.MIGRATION_PHASES``: beyond ``failover_resubmit``,
  every entry must be ``kv_ship:<phase>`` and every phase must appear
  (the tuple is hand-copied in ``cluster.py`` to keep jax out of its
  import graph — this pass is the copy's keeper).

Dynamic names (f-strings like ``kv_ship:{dom}``, variables) are
skipped; the registries' own lockstep rule covers the f-string case.
"""
from __future__ import annotations

import ast

from .core import Check

__all__ = ["TraceNameCheck"]

_HOP_CALLS = ("mint", "child")


class TraceNameCheck(Check):
    id = "PTL008"
    describe = ("tracing name (request-event kind, trace-hop via, "
                "Perfetto counter/flow track, tail cause) not in its "
                "flight-recorder/types registry — a silent join-miss "
                "at read time")

    def __init__(self, registry=None):
        """``registry``: optional override dict (fixture tests) with
        keys ``request_event`` / ``trace_hop`` / ``counter_track`` /
        ``flow_event`` / ``tail_cause`` / ``migration_phase`` (each a
        set); default harvests them from the scanned registry modules
        (with the PTL005/PTL007 import fallback for subtree runs)."""
        self._override = registry
        self.registry = {"request_event": set(), "trace_hop": set(),
                         "counter_track": set(), "flow_event": set(),
                         "tail_cause": set(), "migration_phase": set()}
        self._saw_recorder = False
        self._saw_types = False
        self._saw_transport = False
        self._saw_cluster = False
        self._fallback_done = False

    # -- registry harvesting --------------------------------------------
    @staticmethod
    def _harvest_tuple(tree, name, into):
        """Module-level ``NAME = ("...", ...)`` string tuple/list."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                for e in node.value.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, str):
                        into.add(e.value)

    @staticmethod
    def _harvest_str(tree, name, into):
        """Module-level ``NAME = "..."`` string constant."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                into.add(node.value.value)

    def _harvest_recorder(self, tree, registry):
        self._harvest_tuple(tree, "REQUEST_EVENT_KINDS",
                            registry["request_event"])
        self._harvest_tuple(tree, "COUNTER_TRACKS",
                            registry["counter_track"])
        self._harvest_tuple(tree, "TAIL_CAUSES", registry["tail_cause"])
        self._harvest_str(tree, "FLOW_EVENT_NAME", registry["flow_event"])

    def collect(self, mod):
        if self._override is not None:
            return
        if mod.relpath.endswith("profiler/flight_recorder.py"):
            self._saw_recorder = True
            self._harvest_recorder(mod.tree, self.registry)
        if mod.relpath.endswith("serving/types.py"):
            self._saw_types = True
            self._harvest_tuple(mod.tree, "TRACE_HOP_KINDS",
                                self.registry["trace_hop"])
        if mod.relpath.endswith("serving/kv_transport.py"):
            self._saw_transport = True
            self._harvest_tuple(mod.tree, "MIGRATION_PHASES",
                                self.registry["migration_phase"])
        if mod.relpath.endswith("serving/cluster.py"):
            self._saw_cluster = True
            self._harvest_tuple(mod.tree, "FLEET_TAIL_CAUSES",
                                self.registry["tail_cause"])

    def _registry(self):
        if self._override is not None:
            return self._override
        if not (self._saw_recorder and self._saw_types
                and self._saw_transport and self._saw_cluster) \
                and not self._fallback_done:
            # registry modules not in the scanned tree (fixture dirs,
            # subtree runs): parse the REAL modules' source with the
            # same harvest logic — cached, one parse per run
            self._fallback_done = True
            try:
                if not self._saw_recorder:
                    from ..profiler import flight_recorder as fr
                    with open(fr.__file__, encoding="utf-8") as fh:
                        self._harvest_recorder(ast.parse(fh.read()),
                                               self.registry)
                if not self._saw_types:
                    from ..serving import types as st
                    with open(st.__file__, encoding="utf-8") as fh:
                        self._harvest_tuple(
                            ast.parse(fh.read()), "TRACE_HOP_KINDS",
                            self.registry["trace_hop"])
                if not self._saw_transport:
                    from ..serving import kv_transport as kt
                    with open(kt.__file__, encoding="utf-8") as fh:
                        self._harvest_tuple(
                            ast.parse(fh.read()), "MIGRATION_PHASES",
                            self.registry["migration_phase"])
                if not self._saw_cluster:
                    from ..serving import cluster as cl
                    with open(cl.__file__, encoding="utf-8") as fh:
                        self._harvest_tuple(
                            ast.parse(fh.read()), "FLEET_TAIL_CAUSES",
                            self.registry["tail_cause"])
            except Exception:
                pass
        return self.registry

    # -- call-site checking ---------------------------------------------
    def run(self, mod):
        if not any(tok in mod.text for tok in
                   ("req_event(", ".mint(", ".child(", "_bump_trace(",
                    '"ph"', "cause", "FLEET_TAIL_CAUSES")):
            return          # textual prefilter
        reg = self._registry()
        if not any(reg.get(k) for k in ("request_event", "trace_hop",
                                        "counter_track", "flow_event",
                                        "tail_cause")):
            return          # no registry found at all: nothing to check
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(mod, node, reg)
            elif isinstance(node, ast.Dict):
                yield from self._check_event_dict(mod, node, reg)
            elif isinstance(node, ast.Assign):
                yield from self._check_cause_assign(mod, node, reg)
                yield from self._check_fleet_lockstep(mod, node, reg)
            elif isinstance(node, ast.FunctionDef) and \
                    "classify" in node.name.lower():
                yield from self._check_classify_returns(mod, node, reg)

    def _check_call(self, mod, node, reg):
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        if func.attr == "req_event" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            kind = node.args[1].value
            if kind not in reg.get("request_event", set()):
                yield self.finding(
                    mod, node,
                    f"request-event kind {kind!r} is not in "
                    f"REQUEST_EVENT_KINDS — timelines() consumers "
                    f"grouping by kind silently drop it (add it to "
                    f"flight_recorder.REQUEST_EVENT_KINDS)",
                    key=f"unknown-request-event:{kind}")
        via = None
        if func.attr in _HOP_CALLS and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            via = node.args[0].value
        elif func.attr == "_bump_trace" and node.args and \
                isinstance(node.args[-1], ast.Constant) and \
                isinstance(node.args[-1].value, str):
            via = node.args[-1].value
        if via is not None and via not in reg.get("trace_hop", set()):
            yield self.finding(
                mod, node,
                f"trace-hop via {via!r} is not in TRACE_HOP_KINDS — "
                f"hop provenance grouped by via would fork the "
                f"vocabulary (add it to serving/types.py)",
                key=f"unknown-trace-hop:{via}")

    def _check_event_dict(self, mod, node, reg):
        """Perfetto event dict literals: ``"ph": "C"`` name must be a
        registered counter track; ``"ph": "s"/"f"`` name must be the
        flow-event name. Dynamic names (``**common``) are skipped."""
        lits = {}
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and isinstance(v, ast.Constant) and \
                    isinstance(v.value, str):
                lits[k.value] = v.value
        ph, name = lits.get("ph"), lits.get("name")
        if name is None:
            return
        if ph == "C" and name not in reg.get("counter_track", set()):
            yield self.finding(
                mod, node,
                f"counter track {name!r} is not in COUNTER_TRACKS — "
                f"Perfetto queries on registered tracks miss it",
                key=f"unknown-counter-track:{name}")
        if ph in ("s", "f") and reg.get("flow_event") and \
                name not in reg["flow_event"]:
            yield self.finding(
                mod, node,
                f"flow event named {name!r} — Perfetto matches "
                f"'s'/'f' pairs on (name, cat, id), so a name off "
                f"FLOW_EVENT_NAME breaks the cross-pid arrows",
                key=f"unknown-flow-event:{name}")

    @staticmethod
    def _literal_arms(value):
        """String literals reachable from an assignment RHS: a bare
        constant, or the arms of a conditional expression (the
        ``cause = "x" if ... else _classify(...)`` shape)."""
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            yield value.value
        elif isinstance(value, ast.IfExp):
            yield from TraceNameCheck._literal_arms(value.body)
            yield from TraceNameCheck._literal_arms(value.orelse)

    def _check_cause_assign(self, mod, node, reg):
        causes = reg.get("tail_cause", set())
        if not causes or len(node.targets) != 1:
            return
        t = node.targets[0]
        named = isinstance(t, ast.Name) and t.id == "cause"
        keyed = isinstance(t, ast.Subscript) and \
            isinstance(t.slice, ast.Constant) and t.slice.value == "cause"
        if not (named or keyed):
            return
        for cause in self._literal_arms(node.value):
            if cause not in causes:
                yield self.finding(
                    mod, node,
                    f"tail cause {cause!r} is not in TAIL_CAUSES / "
                    f"FLEET_TAIL_CAUSES — explain_tail consumers "
                    f"keying on registered causes never see it",
                    key=f"unknown-tail-cause:{cause}")

    def _check_classify_returns(self, mod, node, reg):
        causes = reg.get("tail_cause", set())
        if not causes:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and \
                    isinstance(sub.value, ast.Constant) and \
                    isinstance(sub.value.value, str):
                cause = sub.value.value
                if cause not in causes:
                    yield self.finding(
                        mod, sub,
                        f"classifier {node.name} returns cause "
                        f"{cause!r} which is not in TAIL_CAUSES",
                        key=f"unknown-tail-cause:{cause}",
                        func=node.name)

    def _check_fleet_lockstep(self, mod, node, reg):
        """``FLEET_TAIL_CAUSES`` is hand-copied in ``cluster.py`` (to
        keep jax out of its import graph) — hold the copy to
        ``failover_resubmit`` + exactly one ``kv_ship:<phase>`` per
        ``MIGRATION_PHASES`` entry."""
        phases = reg.get("migration_phase", set())
        if not phases or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name) \
                or node.targets[0].id != "FLEET_TAIL_CAUSES" \
                or not isinstance(node.value, (ast.Tuple, ast.List)):
            return
        entries = [e.value for e in node.value.elts
                   if isinstance(e, ast.Constant)
                   and isinstance(e.value, str)]
        covered = set()
        for entry in entries:
            if entry == "failover_resubmit":
                continue
            if not entry.startswith("kv_ship:"):
                yield self.finding(
                    mod, node,
                    f"FLEET_TAIL_CAUSES entry {entry!r} is neither "
                    f"'failover_resubmit' nor a 'kv_ship:<phase>'",
                    key=f"fleet-cause-shape:{entry}")
                continue
            phase = entry.split(":", 1)[1]
            covered.add(phase)
            if phase not in phases:
                yield self.finding(
                    mod, node,
                    f"FLEET_TAIL_CAUSES names ship phase {phase!r} "
                    f"which is not in kv_transport.MIGRATION_PHASES",
                    key=f"fleet-cause-phase:{phase}")
        for phase in sorted(phases - covered):
            yield self.finding(
                mod, node,
                f"MIGRATION_PHASES entry {phase!r} has no "
                f"'kv_ship:{phase}' in FLEET_TAIL_CAUSES — "
                f"explain_tail could emit a cause the fleet registry "
                f"does not declare",
                key=f"fleet-cause-missing:{phase}")
