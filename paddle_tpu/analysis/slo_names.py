"""PTL007 — SLO/pathology strict-name pass.

The sensor layer carries two new dynamic-label name spaces beyond
PTL005's telemetry registry: ``Alert.kind`` (every alert the SLO engine
or a pathology detector may raise) and the labeled gauge FAMILIES
(``slo_burn_rate``/``slo_breached``/``pathology_active``). At runtime
``set_labeled_gauge`` raises ``KeyError`` for an undeclared family, but
an alert kind typo'd at a ``raise_alert``/``clear_alert`` call site (or
a detector class whose ``kind`` drifts from the registry) would only
surface when that pathology actually FIRES — in production, by
definition during an incident. This pass moves the check to lint time:

* every literal first argument of ``.raise_alert(...)`` /
  ``.clear_alert(...)``, every literal ``kind=`` (or first positional)
  of an ``Alert(...)`` construction, and every class-level ``kind =
  "..."`` of a ``*Detector`` class must appear in
  ``paddle_tpu/profiler/metrics_store.py``'s ``ALERT_KINDS`` tuple;
* every literal first argument of ``.set_labeled_gauge(...)`` must be a
  key of ``paddle_tpu/profiler/serving_telemetry.py``'s
  ``LABELED_GAUGE_FAMILIES`` dict.

Dynamic names (variables, f-strings — e.g. a detector raising
``self.kind``) are skipped; the runtime contract still covers those
through the class-level ``kind`` literal this pass DOES check.
"""
from __future__ import annotations

import ast

from .core import Check

__all__ = ["SLONameCheck"]

_ALERT_CALLS = ("raise_alert", "clear_alert")


class SLONameCheck(Check):
    id = "PTL007"
    describe = ("SLO/pathology metric or Alert.kind not in the "
                "ALERT_KINDS / LABELED_GAUGE_FAMILIES registries "
                "(today a fire-time-only failure)")

    def __init__(self, registry=None):
        """``registry``: optional {"alert_kind": set, "labeled_gauge":
        set} override (fixture tests); default parses the registries
        out of the scanned ``metrics_store.py`` /
        ``serving_telemetry.py`` (with an import fallback for subtree
        runs, like PTL005)."""
        self._override = registry
        self.registry = {"alert_kind": set(), "labeled_gauge": set()}
        self._saw_kinds = False
        self._saw_families = False
        self._fallback_done = False

    # -- registry harvesting --------------------------------------------
    @staticmethod
    def _harvest_kinds(tree, registry):
        """``ALERT_KINDS = ("...", ...)`` module-level tuple."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "ALERT_KINDS" \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                for e in node.value.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, str):
                        registry["alert_kind"].add(e.value)

    @staticmethod
    def _harvest_families(tree, registry):
        """``LABELED_GAUGE_FAMILIES = {"name": "label", ...}``."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "LABELED_GAUGE_FAMILIES" \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        registry["labeled_gauge"].add(k.value)

    def collect(self, mod):
        if self._override is not None:
            return
        if mod.relpath.endswith("metrics_store.py"):
            self._saw_kinds = True
            self._harvest_kinds(mod.tree, self.registry)
        if mod.relpath.endswith("serving_telemetry.py"):
            self._saw_families = True
            self._harvest_families(mod.tree, self.registry)

    def _registry(self):
        if self._override is not None:
            return self._override
        if (not self._saw_kinds or not self._saw_families) \
                and not self._fallback_done:
            # registry modules not in the scanned tree (fixture dirs,
            # subtree runs): parse the REAL modules' source with the
            # same harvest logic — cached, one parse per run
            self._fallback_done = True
            try:
                if not self._saw_kinds:
                    from ..profiler import metrics_store as ms
                    with open(ms.__file__, encoding="utf-8") as fh:
                        self._harvest_kinds(ast.parse(fh.read()),
                                            self.registry)
                if not self._saw_families:
                    from ..profiler import serving_telemetry as st
                    with open(st.__file__, encoding="utf-8") as fh:
                        self._harvest_families(ast.parse(fh.read()),
                                               self.registry)
            except Exception:
                pass
        return self.registry

    # -- call-site checking ---------------------------------------------
    def run(self, mod):
        if not any(tok in mod.text for tok in
                   ("raise_alert(", "clear_alert(", "set_labeled_gauge(",
                    "Alert(", "Detector")):     # textual prefilter
            return
        reg = self._registry()
        if not reg.get("alert_kind") and not reg.get("labeled_gauge"):
            return          # no registry found at all: nothing to check
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(mod, node, reg)
            elif isinstance(node, ast.ClassDef) and \
                    node.name.endswith("Detector"):
                yield from self._check_detector_class(mod, node, reg)

    def _check_call(self, mod, node, reg):
        kinds = reg.get("alert_kind", set())
        fams = reg.get("labeled_gauge", set())
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _ALERT_CALLS and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                kind = node.args[0].value
                if kind not in kinds:
                    yield self.finding(
                        mod, node,
                        f"alert kind {kind!r} is not in ALERT_KINDS — "
                        f"an undeclared kind only surfaces when the "
                        f"alert fires (add it to metrics_store"
                        f".ALERT_KINDS)",
                        key=f"unknown-alert-kind:{kind}")
            if func.attr == "set_labeled_gauge" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                fam = node.args[0].value
                if fam not in fams:
                    yield self.finding(
                        mod, node,
                        f"labeled gauge family {fam!r} is not in "
                        f"LABELED_GAUGE_FAMILIES — this call raises "
                        f"KeyError the first time this path runs",
                        key=f"unknown-labeled-gauge:{fam}")
        # Alert(kind=...) / Alert("kind", ...) direct constructions
        if isinstance(func, ast.Name) and func.id == "Alert":
            kind = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                kind = node.args[0].value
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    kind = kw.value.value
            if kind is not None and kind not in kinds:
                yield self.finding(
                    mod, node,
                    f"Alert kind {kind!r} is not in ALERT_KINDS",
                    key=f"unknown-alert-kind:{kind}")

    def _check_detector_class(self, mod, node, reg):
        kinds = reg.get("alert_kind", set())
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "kind" \
                    and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, str):
                kind = stmt.value.value
                if kind in ("unnamed",):    # the abstract base's stub
                    continue
                if kind not in kinds:
                    yield self.finding(
                        mod, stmt,
                        f"detector class {node.name} declares kind "
                        f"{kind!r} which is not in ALERT_KINDS — its "
                        f"alerts and pathology_active label would be "
                        f"unregistered schema",
                        key=f"unknown-alert-kind:{kind}",
                        func=node.name)
