"""PTL005 — telemetry strict-name pass.

``ServingTelemetry`` is strict at runtime: ``add_stage``/``inc``/
``set_gauge`` raise ``KeyError`` for a name never declared, and
``observe`` resolves its histogram via ``getattr`` (an AttributeError
for a typo). Strictness at runtime means the typo is found when the
code PATH runs — for a rarely-taken branch that is three rounds later,
in production. This pass moves the check to lint time: every
string-literal name at a telemetry call site must exist in the registry
parsed out of ``serving_telemetry.py`` (module-level ``STAGES`` /
``GAUGES`` / ``_COUNTERS`` tuples plus ``self.<hist> =
LatencyHistogram()`` assignments), or be declared via a literal
``.register("<kind>", "<name>")`` call somewhere in the scanned tree.

Dynamic names (f-strings, variables) are skipped — the runtime contract
still covers those.
"""
from __future__ import annotations

import ast

from .core import Check

__all__ = ["TelemetryNameCheck"]

#: telemetry write methods -> registry kind they draw names from
_SETTERS = {"add_stage": "stage", "inc": "counter",
            "set_gauge": "gauge", "observe": "histogram",
            "stage": "stage"}

#: receivers considered telemetry objects (call sites look like
#: ``self.telemetry.inc(...)`` / ``tel.set_gauge(...)``)
_RECEIVERS = ("telemetry", "tel")


def _is_telemetry_receiver(node):
    if isinstance(node, ast.Name):
        return node.id in _RECEIVERS
    if isinstance(node, ast.Attribute):
        return node.attr in _RECEIVERS
    return False


class TelemetryNameCheck(Check):
    id = "PTL005"
    describe = ("telemetry stage/counter/gauge/histogram name not in the "
                "ServingTelemetry registry (today a runtime-only "
                "KeyError)")

    def __init__(self, registry=None):
        """``registry``: optional {"stage"|"counter"|"gauge"|"histogram"
        -> set of names} override (fixture tests); default parses the
        registry out of the scanned ``serving_telemetry.py``."""
        self._override = registry
        self.registry = {"stage": set(), "counter": set(),
                         "gauge": set(), "histogram": set()}
        self._saw_registry_module = False
        self._fallback_reg = None       # cached import-fallback registry

    @staticmethod
    def _parse_registry_tree(tree, registry):
        """Harvest STAGES/GAUGES/_COUNTERS tuples and ``self.<name> =
        LatencyHistogram()`` assignments out of a serving_telemetry AST
        — THE one copy of the registry-parsing logic, shared by the
        in-tree scan and the import fallback (a hardcoded name set
        would silently drift the next time a histogram is added)."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and t.id in (
                        "STAGES", "GAUGES", "_COUNTERS") and \
                        isinstance(node.value, (ast.Tuple, ast.List)):
                    kind = {"STAGES": "stage", "GAUGES": "gauge",
                            "_COUNTERS": "counter"}[t.id]
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            registry[kind].add(e.value)
                if isinstance(t, ast.Attribute) and \
                        isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func, ast.Name) and \
                        node.value.func.id == "LatencyHistogram":
                    registry["histogram"].add(t.attr)

    # -- phase 1: build the registry ------------------------------------
    def collect(self, mod):
        if self._override is not None:
            return
        if mod.relpath.endswith("serving_telemetry.py"):
            self._saw_registry_module = True
            self._parse_registry_tree(mod.tree, self.registry)
        # extension names declared anywhere via register("kind", "name")
        if ".register(" not in mod.text:        # textual prefilter
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "register" and \
                    len(node.args) >= 2 and \
                    all(isinstance(a, ast.Constant) and
                        isinstance(a.value, str) for a in node.args[:2]):
                kind, name = node.args[0].value, node.args[1].value
                if kind in self.registry:
                    self.registry[kind].add(name)

    # -- phase 2: check call sites --------------------------------------
    def run(self, mod):
        if not any(s in mod.text for s in
                   ("add_stage(", ".inc(", "set_gauge(", ".observe(",
                    ".stage(")):                # textual prefilter
            return
        reg = self._override if self._override is not None else \
            self.registry
        if self._override is None and not self._saw_registry_module:
            # registry not in the scanned tree (fixture dirs, subtree
            # runs): fall back to parsing the REAL module's source with
            # the same harvest logic as the in-tree scan (cached — one
            # parse per run, not one per scanned module)
            if self._fallback_reg is None:
                try:
                    from ..profiler import serving_telemetry as st
                    with open(st.__file__, encoding="utf-8") as fh:
                        st_tree = ast.parse(fh.read())
                    reg = {"stage": set(), "gauge": set(),
                           "counter": set(), "histogram": set()}
                    self._parse_registry_tree(st_tree, reg)
                    for k in self.registry:      # keep register() names
                        reg[k] |= self.registry[k]
                    self._fallback_reg = reg
                except Exception:
                    self._fallback_reg = {}
            if not self._fallback_reg:
                return
            reg = self._fallback_reg
        if mod.relpath.endswith("serving_telemetry.py"):
            return          # the registry itself (error-message literals)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SETTERS
                    and _is_telemetry_receiver(node.func.value)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            kind = _SETTERS[node.func.attr]
            name = node.args[0].value
            if name not in reg.get(kind, set()):
                yield self.finding(
                    mod, node,
                    f"telemetry {kind} {name!r} is not in the "
                    f"ServingTelemetry registry — this call raises "
                    f"{'AttributeError' if kind == 'histogram' else 'KeyError'} "
                    f"the first time this path runs (declare it in "
                    f"serving_telemetry.py or via register())",
                    key=f"unknown-{kind}:{name}")
