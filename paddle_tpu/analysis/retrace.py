"""PTL002 — retrace / concretization hazard detector.

The serving engine's throughput story assumes every jitted program
compiles ONCE per (shape, flag) signature. The ways that assumption
historically broke here:

* **Python control flow on traced values** — ``if jnp.any(x):`` raises
  a ConcretizationTypeError under jit, and OUTSIDE jit it silently
  becomes a per-call device sync plus, when fed into a static argument,
  a retrace per distinct value.
* **Unhashable statics** (the PR-3 ``slice`` bug class) — passing a
  ``slice``/list/dict as a ``static_argnums`` argument either crashes
  at the jit cache lookup or, for types with value-hash semantics,
  retraces per call.
* **Trace-time impurity** — ``time.time()``/``np.random.*`` inside a
  jit body bakes one sample into the compiled program; the bench then
  measures a constant and calls it jitter.
* **Closure-captured mutables** — a list/dict captured by a jit body is
  baked at trace time; later host mutation silently diverges from the
  compiled constant.

Jit bodies are found syntactically: functions decorated with
``@jax.jit``/``@partial(jax.jit, ...)`` and functions passed by name to
``jax.jit(...)`` anywhere in the same module (the engine's
``_programs`` idiom).
"""
from __future__ import annotations

import ast

from .core import Check

__all__ = ["RetraceCheck"]

_IMPURE_CALLS = {
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
    ("datetime", "now"), ("datetime", "utcnow"),
    ("random", "random"), ("random", "randint"), ("random", "choice"),
    ("random", "uniform"),
}

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)

#: jnp/jax attributes whose results are STATIC metadata (dtype/shape/
#: topology introspection) — branching on them never concretizes a
#: traced value
_STATIC_JAX_CALLS = frozenset({
    "issubdtype", "isdtype", "result_type", "promote_types", "dtype",
    "ndim", "shape", "size", "iscomplexobj",
    "process_count", "process_index", "device_count",
    "local_device_count", "default_backend", "devices", "local_devices",
})


def _call_chain(call):
    """('np', 'random', 'normal') for np.random.normal(...) etc."""
    parts = []
    node = call.func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return tuple(reversed(parts))


def _is_jax_jit(node):
    """True for the expression ``jax.jit`` / ``jit`` / ``pjit``."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit") and \
            isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id in ("jit", "pjit")


def _jit_call_of(node):
    """The ``jax.jit(...)`` Call inside ``node``, unwrapping
    ``partial(jax.jit, ...)`` decorators; None otherwise."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jax_jit(node.func):
        return node
    chain = _call_chain(node)
    if chain and chain[-1] == "partial" and node.args and \
            _is_jax_jit(node.args[0]):
        return node
    return None


def _static_positions(jit_call):
    """Literal static_argnums positions of a jax.jit(...) call."""
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
    return ()


def _is_unhashable_literal(node):
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    return isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
        and node.func.id in ("slice", "list", "dict", "set", "bytearray")


class RetraceCheck(Check):
    id = "PTL002"
    describe = ("retrace/concretization hazard: python branches on "
                "traced values, unhashable statics, trace-time "
                "impurity, closure-captured mutables")

    def run(self, mod):
        # textual prefilter: no jax/jnp mention -> nothing to trace
        has_jax = "jax" in mod.text or "jnp" in mod.text
        has_jit = "jit" in mod.text
        if not has_jax:
            return
        jitted_names = set()         # function names passed to jax.jit
        jit_bound = {}               # local name -> jax.jit(...) Call
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_jax_jit(node.func) \
                    and node.args and isinstance(node.args[0], ast.Name):
                jitted_names.add(node.args[0].id)
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                call = _jit_call_of(node.value)
                if call is not None:
                    jit_bound[node.targets[0].id] = call
            # (a) python `if`/`while` whose test calls into jnp/jax —
            # the test concretizes a traced value (ConcretizationError
            # under jit; a silent per-call sync outside it)
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                # a call consumed only through `.dtype`/`.shape`/`.ndim`
                # contributes static metadata, not a traced value
                meta_only = set()
                for sub in ast.walk(node.test):
                    if isinstance(sub, ast.Attribute) and sub.attr in (
                            "dtype", "shape", "ndim", "size"):
                        for inner in ast.walk(sub.value):
                            meta_only.add(id(inner))
                for sub in ast.walk(node.test):
                    if isinstance(sub, ast.Call) and \
                            id(sub) not in meta_only:
                        chain = _call_chain(sub)
                        if chain and chain[0] in ("jnp", "jax") and \
                                chain[-1] not in _STATIC_JAX_CALLS:
                            yield self.finding(
                                mod, node.test,
                                f"python {type(node).__name__.lower()} on "
                                f"a traced value: "
                                f"`{mod.segment(node.test)}` (use "
                                f"jnp.where / lax.cond)",
                                key=mod.segment(node.test))
                            break
        # (b) hazards INSIDE jit bodies
        if not has_jit:
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                decorated = any(
                    _is_jax_jit(d) or _jit_call_of(d) is not None
                    for d in node.decorator_list)
                if decorated or node.name in jitted_names:
                    yield from self._scan_jit_body(mod, node)
        # (c) unhashable static arguments at call sites of jit-bound
        # names (the PR-3 slice bug class: the jit cache either crashes
        # hashing it or retraces per identity)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jit_bound):
                continue
            static = _static_positions(jit_bound[node.func.id])
            for pos in static:
                if pos < len(node.args) and \
                        _is_unhashable_literal(node.args[pos]):
                    yield self.finding(
                        mod, node.args[pos],
                        f"unhashable/mutable value at static_argnums "
                        f"position {pos} of `{node.func.id}`: "
                        f"`{mod.segment(node.args[pos])}` retraces per "
                        f"call (or crashes the jit cache hash)",
                        key=f"static-arg:{node.func.id}:{pos}:"
                            f"{mod.segment(node.args[pos])}")

    def _scan_jit_body(self, mod, fn):
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = _call_chain(node)
                if len(chain) >= 2 and (chain[-2], chain[-1]) in \
                        _IMPURE_CALLS:
                    yield self.finding(
                        mod, node,
                        f"impure call `{'.'.join(chain)}` inside jit "
                        f"body `{fn.name}` is baked in at trace time",
                        key=f"impure:{fn.name}:{'.'.join(chain)}",
                        func=fn.name)
                elif len(chain) >= 2 and chain[0] == "np" and \
                        chain[1] == "random":
                    yield self.finding(
                        mod, node,
                        f"`{'.'.join(chain)}` inside jit body "
                        f"`{fn.name}` samples ONCE at trace time (use "
                        f"jax.random with a traced key)",
                        key=f"impure:{fn.name}:{'.'.join(chain)}",
                        func=fn.name)
        # closure-captured mutables: names assigned to mutable literals
        # in an ENCLOSING scope that this jit body loads AND that the
        # enclosing scope mutates after the body is defined (the
        # build-then-capture idiom — a dict frozen before the def — is
        # benign: nothing can diverge from the traced constant)
        parent = getattr(fn, "_ptlint_parent", None)
        if parent is None:
            return
        mutable_outer = {}
        for stmt in ast.walk(parent):
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, _MUTABLE_LITERALS):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        mutable_outer[t.id] = stmt
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Tuple):
                for t in stmt.targets:
                    if isinstance(t, ast.Tuple) and \
                            len(t.elts) == len(stmt.value.elts):
                        for te, ve in zip(t.elts, stmt.value.elts):
                            if isinstance(te, ast.Name) and \
                                    isinstance(ve, _MUTABLE_LITERALS):
                                mutable_outer[te.id] = stmt
        if not mutable_outer:
            return
        end = getattr(fn, "end_lineno", fn.lineno)
        mutated_after = set()
        for node in ast.walk(parent):
            if getattr(node, "lineno", 0) <= end:
                continue
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("append", "extend", "add", "pop",
                                       "update", "insert", "remove",
                                       "clear", "setdefault") and \
                    isinstance(node.func.value, ast.Name):
                mutated_after.add(node.func.value.id)
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = node.targets if not isinstance(
                    node, ast.AugAssign) else [node.target]
                for t in targets:
                    while isinstance(t, ast.Subscript):
                        t = t.value
                    if isinstance(t, ast.Name):
                        mutated_after.add(t.id)
        mutable_outer = {k: v for k, v in mutable_outer.items()
                         if k in mutated_after}
        if not mutable_outer:
            return
        local = set(params)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Store):
                local.add(node.id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in mutable_outer and node.id not in local:
                yield self.finding(
                    mod, node,
                    f"jit body `{fn.name}` closes over mutable "
                    f"`{node.id}` (baked at trace time; later host "
                    f"mutation silently diverges)",
                    key=f"closure:{fn.name}:{node.id}", func=fn.name)
                break

    def collect(self, mod):
        # annotate nested function defs with their immediate enclosing
        # function so the closure scan can look one scope up — one
        # linear pass with an explicit (node, enclosing) stack
        if "jit" not in mod.text:
            return
        stack = [(mod.tree, None)]
        while stack:
            node, enclosing = stack.pop()
            is_fn = isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
            if is_fn and enclosing is not None:
                node._ptlint_parent = enclosing
            inner = node if is_fn else enclosing
            for child in ast.iter_child_nodes(node):
                stack.append((child, inner))
