"""Checker framework for ``paddle_tpu.analysis`` — the project-specific
static-analysis layer (reference analog: the custom flake8/pylint plugin
layer real frameworks ship around their core, PAPER.md layers 4-5).

The framework is deliberately AST-only and import-free for the code it
scans: it parses source text, never executes it, so it can run on a
cold CPU box in well under the tier-1 budget and can analyze fixture
snippets that would not even import.

Pieces:

* :class:`Finding` — one diagnosed violation, with a line-number-free
  *fingerprint* (check | path | function | normalized snippet) so the
  checked-in baseline survives unrelated edits that shift line numbers.
* :class:`SourceModule` — parsed file + per-line suppression table.
  ``# ptlint: disable=PTL001 -- reason`` on (or immediately above) a
  line suppresses that check there; a suppression WITHOUT a reason
  string is itself reported (PTL000) — the policy is that every
  grandfathered sync/hazard names why it is deliberate.
* :class:`Check` — base class. ``collect`` runs over every module first
  (cross-module registries: telemetry names, lock edges), then ``run``
  emits per-module findings, then ``finalize`` emits cross-module ones
  (lock-order cycles).
* baseline — ``analysis_baseline.json`` maps fingerprints to counts;
  findings covered by the baseline are reported but do not fail the
  run. ``--write-baseline`` regenerates it; stale entries (fingerprints
  no longer produced) are listed so burn-down is visible.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

__all__ = ["Finding", "SourceModule", "Check", "Report", "run_analysis",
           "load_baseline", "iter_py_files", "JSON_SCHEMA_VERSION"]

#: bumped only when the JSON report layout changes incompatibly —
#: tests/test_analysis.py pins it (schema stability is part of the
#: contract: CI parses this output)
JSON_SCHEMA_VERSION = 1

_SUPPRESS_RE = re.compile(
    r"#\s*ptlint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*(?:--|—)\s*(?P<reason>\S.*?))?\s*$")


@dataclasses.dataclass
class Finding:
    """One diagnosed violation of a project invariant."""
    check: str            # "PTL001"
    path: str             # fingerprint-stable relative path
    line: int
    col: int
    func: str             # enclosing function, "<module>" at top level
    message: str
    key: str              # normalized offending snippet (stable)
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False

    @property
    def fingerprint(self):
        return f"{self.check}|{self.path}|{self.func}|{self.key}"

    @property
    def new(self):
        """True when nothing grandfathers this finding — these fail the
        run."""
        return not (self.suppressed or self.baselined)

    def to_json(self):
        return {"check": self.check, "path": self.path, "line": self.line,
                "col": self.col, "func": self.func, "message": self.message,
                "key": self.key, "fingerprint": self.fingerprint,
                "suppressed": self.suppressed,
                "suppress_reason": self.suppress_reason,
                "baselined": self.baselined, "new": self.new}

    def render(self):
        tag = "suppressed" if self.suppressed else \
            ("baselined" if self.baselined else "NEW")
        return (f"{self.path}:{self.line}:{self.col}: {self.check} "
                f"[{tag}] {self.message}")


def _norm_key(text, limit=100):
    """Whitespace-collapsed snippet, truncated — the fingerprint's
    line-number-free identity component."""
    key = " ".join(str(text).split())
    return key[:limit]


class SourceModule:
    """One parsed source file plus its suppression table."""

    def __init__(self, path, relpath, text):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        #: line -> {check_id or "all": reason-or-""}
        self.suppressions = {}
        #: lines whose suppression comment carries no reason (PTL000)
        self.bare_suppressions = []
        for i, line in self._suppression_comments():
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            checks = {c.strip().upper() for c in m.group(1).split(",")
                      if c.strip()}
            reason = (m.group("reason") or "").strip()
            target = i
            if line.lstrip().startswith("#"):
                # comment-only line: the suppression governs the next
                # CODE line (reasons may wrap onto continuation
                # comments; intervening blank lines don't detach it)
                target = i + 1
                while target <= len(self.lines):
                    nxt = self.lines[target - 1].strip()
                    if nxt and not nxt.startswith("#"):
                        break
                    target += 1
            entry = self.suppressions.setdefault(target, {})
            for c in checks:
                entry[c] = reason
            if not reason:
                self.bare_suppressions.append((i, sorted(checks)))

    def _suppression_comments(self):
        """``(line_no, source_line)`` for lines whose suppression marker
        sits in an actual COMMENT token — 'ptlint: disable' text inside
        a docstring or string literal documents the syntax, it neither
        suppresses anything nor trips PTL000 (noqa-style linters use
        the same tokenize discipline)."""
        if "ptlint" not in self.text:       # fast path: no tokenizing
            return
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT and "ptlint" in tok.string:
                    yield tok.start[0], self.lines[tok.start[0] - 1]
        except (tokenize.TokenError, IndentationError):
            # untokenizable tail (shouldn't happen on ast-parseable
            # source): fall back to the raw line scan
            for i, line in enumerate(self.lines, start=1):
                if "ptlint" in line:
                    yield i, line

    def suppression_for(self, check_id, line):
        entry = self.suppressions.get(line)
        if not entry:
            return None
        if check_id in entry:
            return entry[check_id]
        if "ALL" in entry:
            return entry["ALL"]
        return None

    def segment(self, node):
        seg = ast.get_source_segment(self.text, node)
        if seg is None:
            seg = f"<{type(node).__name__}>"
        return _norm_key(seg)


class Check:
    """Base class for one analysis pass. Subclasses set ``id`` and
    ``describe`` and override ``run`` (and optionally ``collect`` /
    ``finalize`` for cross-module state)."""

    id = "PTL???"
    describe = ""

    def collect(self, mod):       # pragma: no cover - default no-op
        pass

    def run(self, mod):
        return ()

    def finalize(self):
        return ()

    def finding(self, mod, node, message, key=None, func="<module>"):
        return Finding(self.id, mod.relpath, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), func, message,
                       key if key is not None else mod.segment(node))


class _SuppressionPolicy(Check):
    """PTL000 — a ``ptlint: disable`` comment with no reason string.

    Suppressions are the grandfathering mechanism for deliberate
    violations; one without a reason hides a finding while recording
    nothing, so the policy check makes the missing reason itself a
    finding (suppressible only via the baseline, on purpose)."""

    id = "PTL000"
    describe = "suppression comments must carry a reason string"

    def run(self, mod):
        for line, checks in mod.bare_suppressions:
            yield Finding(
                self.id, mod.relpath, line, 0, "<module>",
                f"suppression of {','.join(checks)} carries no reason "
                f"string (append `-- why this site is deliberate`)",
                key=f"bare-suppression:{','.join(checks)}")


def _package_base(dirpath):
    """Nearest ancestor of ``dirpath`` that is NOT itself a package
    (no ``__init__.py``) — relpaths are PACKAGE-ROOTED, so linting
    ``paddle_tpu/inference/llm_engine.py`` alone yields the same
    ``paddle_tpu/inference/llm_engine.py`` fingerprint (and allowlist
    suffix) as the whole-tree scan."""
    base = dirpath
    while os.path.isfile(os.path.join(base, "__init__.py")):
        parent = os.path.dirname(base)
        if parent == base:
            break
        base = parent
    return base


def iter_py_files(paths):
    """Yield ``(abs_path, relpath)`` for every ``.py`` under ``paths``.

    ``relpath`` is computed against the argument's package root (the
    nearest non-package ancestor — see :func:`_package_base`), so
    ``python -m paddle_tpu.analysis paddle_tpu/``, a subdirectory run
    and a single-file run all yield identical ``paddle_tpu/...``
    fingerprints no matter the working directory."""
    seen = set()
    for arg in paths:
        root = os.path.abspath(arg)
        if os.path.isfile(root):
            base = _package_base(os.path.dirname(root))
            files = [root]
        else:
            root = root.rstrip(os.sep) or root
            base = _package_base(root) if os.path.isfile(
                os.path.join(root, "__init__.py")) \
                else (os.path.dirname(root) or root)
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        for f in files:
            if f in seen:
                continue
            seen.add(f)
            yield f, os.path.relpath(f, base).replace(os.sep, "/")


def load_baseline(path):
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "fingerprints" not in data:
        raise ValueError(f"{path}: not an analysis baseline "
                         f"(missing 'fingerprints')")
    return dict(data["fingerprints"])


def default_checks():
    from .donation import DonationCheck
    from .host_sync import HostSyncCheck
    from .kv_transfer import KVTransferCheck
    from .locks import LockDisciplineCheck
    from .retrace import RetraceCheck
    from .slo_names import SLONameCheck
    from .telemetry_names import TelemetryNameCheck
    from .trace_names import TraceNameCheck
    return [_SuppressionPolicy(), HostSyncCheck(), RetraceCheck(),
            DonationCheck(), LockDisciplineCheck(), TelemetryNameCheck(),
            KVTransferCheck(), SLONameCheck(), TraceNameCheck()]


class Report:
    """The outcome of one analysis run."""

    def __init__(self, findings, checks, lock_graph=None,
                 stale_baseline=None, parse_errors=None):
        self.findings = findings
        self.checks = checks
        self.lock_graph = lock_graph or {}
        self.stale_baseline = stale_baseline or {}
        self.parse_errors = parse_errors or []

    @property
    def new_findings(self):
        return [f for f in self.findings if f.new]

    @property
    def exit_code(self):
        return 1 if (self.new_findings or self.parse_errors) else 0

    def summary(self):
        n = self.findings
        return {"total": len(n),
                "new": sum(1 for f in n if f.new),
                "suppressed": sum(1 for f in n if f.suppressed),
                "baselined": sum(1 for f in n if f.baselined),
                "stale_baseline": sum(self.stale_baseline.values()),
                "parse_errors": len(self.parse_errors)}

    def to_json(self):
        return {"version": JSON_SCHEMA_VERSION,
                "checks": [{"id": c.id, "describe": c.describe}
                           for c in self.checks],
                "summary": self.summary(),
                "findings": [f.to_json() for f in self.findings],
                "stale_baseline": dict(self.stale_baseline),
                "lock_order_graph": self.lock_graph,
                "parse_errors": list(self.parse_errors)}

    def render(self, show_all=False):
        lines = []
        for f in self.findings:
            if show_all or f.new:
                lines.append(f.render())
        for path, err in self.parse_errors:
            lines.append(f"{path}:0:0: PARSE-ERROR {err}")
        s = self.summary()
        lines.append(
            f"ptlint: {s['total']} findings "
            f"(new {s['new']}, suppressed {s['suppressed']}, "
            f"baselined {s['baselined']}, "
            f"stale-baseline {s['stale_baseline']})")
        return "\n".join(lines)

    def baseline_json(self):
        counts = {}
        for f in self.findings:
            if not f.suppressed:
                counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
        return {"version": JSON_SCHEMA_VERSION,
                "comment": "grandfathered paddle_tpu.analysis findings — "
                           "burn this file down, never grow it",
                "fingerprints": dict(sorted(counts.items()))}


def run_analysis(paths, checks=None, baseline=None):
    """Run every check over every ``.py`` file under ``paths``.

    ``baseline``: dict fingerprint->count (see :func:`load_baseline`) or
    None. Returns a :class:`Report`; ``report.exit_code`` is non-zero
    iff any finding is neither suppressed nor baselined."""
    if checks is None:
        checks = default_checks()
    mods, parse_errors = [], []
    for path, rel in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            mods.append(SourceModule(path, rel, text))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            parse_errors.append((rel, f"{type(e).__name__}: {e}"))
    for check in checks:
        for mod in mods:
            check.collect(mod)
    findings = []
    for mod in mods:
        for check in checks:
            for f in check.run(mod) or ():
                # PTL000 is deliberately NOT inline-suppressible: a
                # bare suppression listing PTL000 itself must not hide
                # the missing-reason finding (baseline-only escape)
                reason = None if f.check == "PTL000" else \
                    mod.suppression_for(f.check, f.line)
                if reason is not None:
                    f.suppressed = True
                    f.suppress_reason = reason
                findings.append(f)
    for check in checks:
        findings.extend(check.finalize() or ())
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.key))
    stale = {}
    if baseline:
        allowance = dict(baseline)
        for f in findings:
            if f.suppressed:
                continue
            if allowance.get(f.fingerprint, 0) > 0:
                allowance[f.fingerprint] -= 1
                f.baselined = True
        stale = {fp: n for fp, n in allowance.items() if n > 0}
    lock_graph = {}
    for check in checks:
        graph = getattr(check, "lock_graph_json", None)
        if callable(graph):
            lock_graph = graph()
    return Report(findings, checks, lock_graph=lock_graph,
                  stale_baseline=stale, parse_errors=parse_errors)
