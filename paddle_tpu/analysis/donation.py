"""PTL003 — donated-buffer use-after-donation checker.

``donate_argnums`` hands an input buffer's memory to the compiled
program; the caller's array is DELETED the moment the call dispatches.
Reading it afterwards raises a RuntimeError on TPU — but works by
accident on the CPU test backend (no aliasing there), which is exactly
how this bug class ships: green tier-1, dead on the pod. (PR 7's rule
that donation-consumed engine buffers are rebuilt only via ``reset()``
exists because of this.)

The check is flow-lite, scope-local dataflow: inside one function (or
module) body it tracks

* names bound to ``jax.jit(..., donate_argnums=...)`` / immediate
  ``jax.jit(f, donate_argnums=...)(args)`` calls, and
* call sites of those names — the argument expression at each donated
  position (bare names and ``self.<attr>`` chains) is marked consumed
  at the call line, and

flags any later ``Load`` of a consumed value with no intervening
rebind. The canonical safe idiom — ``x = donating_fn(x)`` /
``self.A, ... = self._set_fn(self.A, ...)`` — rebinds on the call line
and stays clean by construction.
"""
from __future__ import annotations

import ast

from .core import Check
from .retrace import _jit_call_of

__all__ = ["DonationCheck"]


def _donated_positions(jit_call):
    for kw in jit_call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
    return ()


def _ref_key(node):
    """Trackable identity of an argument expression: a bare name
    ('x',) or a self-attribute chain ('self', 'buf'). None = not a
    trackable reference (a literal, a call result, a subscript)."""
    if isinstance(node, ast.Name):
        return (node.id,)
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name):
        return (node.value.id, node.attr)
    return None


class DonationCheck(Check):
    id = "PTL003"
    describe = ("donated buffer read after the donating call (works on "
                "CPU, RuntimeError on TPU)")

    def run(self, mod):
        if "donate_argnums" not in mod.text:    # textual prefilter
            return
        yield from self._scan_scope(mod, mod.tree, "<module>")
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan_scope(mod, node, node.name)

    def _scan_scope(self, mod, scope, func):
        # pass 1: donating callables bound in this scope
        donating = {}                          # name -> donated positions

        def walk_scope(node):
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                yield from walk_scope(child)

        scope_nodes = []
        for n in (scope.body if hasattr(scope, "body") else []):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue        # nested scopes get their own scan
            scope_nodes.extend(walk_scope(n))

        for node in scope_nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                call = _jit_call_of(node.value)
                if call is not None:
                    pos = _donated_positions(call)
                    if pos:
                        key = _ref_key(node.targets[0])
                        if key is not None:
                            donating[key] = pos
        # pass 2: walk events in line order
        consumed = {}                 # ref key -> (donate line, fn name)
        events = []                   # (line, kind, payload)
        for node in scope_nodes:
            if isinstance(node, ast.Call):
                fn_key = _ref_key(node.func)
                pos = None
                label = None
                if fn_key is not None and fn_key in donating:
                    pos = donating[fn_key]
                    label = ".".join(fn_key)
                else:
                    call = _jit_call_of(node.func)
                    if call is not None:
                        pos = _donated_positions(call)
                        label = "jax.jit(...)"
                if pos:
                    for p in pos:
                        if p < len(node.args):
                            key = _ref_key(node.args[p])
                            if key is not None:
                                events.append(
                                    (node.lineno, "donate", (key, label)))
            if isinstance(node, (ast.Name, ast.Attribute)):
                key = _ref_key(node)
                if key is None:
                    continue
                if isinstance(node.ctx, ast.Store):
                    events.append((node.lineno, "store", (key, node)))
                elif isinstance(node.ctx, ast.Load):
                    events.append((node.lineno, "load", (key, node)))
        # same-line ordering makes the canonical `x = f(x)` idiom clean:
        # the read happens BEFORE the donation, the rebind after it
        rank = {"load": 0, "donate": 1, "store": 2}
        events.sort(key=lambda e: (e[0], rank[e[1]]))
        findings = []
        for line, kind, payload in events:
            if kind == "donate":
                key, label = payload
                consumed[key] = (line, label)
            elif kind == "store":
                key, _ = payload
                # a rebind (including the donating call's own result
                # assignment on the same line) revives the name
                consumed.pop(key, None)
            elif kind == "load":
                key, node = payload
                hit = consumed.get(key)
                if hit is not None and line > hit[0]:
                    findings.append(self.finding(
                        mod, node,
                        f"`{'.'.join(key)}` was donated to `{hit[1]}` "
                        f"on line {hit[0]} and read here without a "
                        f"rebind — its buffer is deleted on TPU",
                        key=f"use-after-donate:{'.'.join(key)}:{hit[1]}",
                        func=func))
                    consumed.pop(key, None)     # one finding per donation
        return findings
