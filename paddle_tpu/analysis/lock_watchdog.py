"""Runtime lock-order watchdog — the dynamic half of PTL004.

The static pass sees only LEXICAL nesting of ``with <lock>:`` blocks; a
lock acquired inside a function *called* under another lock is
invisible to it. This watchdog records the acquisition edges that
actually happen: armed by ``PADDLE_TPU_LOCK_CHECKS=1`` (the test
conftest's debug posture, like ``PADDLE_TPU_POOL_CHECKS``), the serving
stack's documented locks are wrapped in :class:`TrackedLock` via
:func:`tracked`, each thread keeps a stack of held lock labels, and
every acquisition while holding another lock records a ``held ->
acquired`` edge.

Two assertions:

* **acyclic online** — an acquisition whose edge closes a cycle in the
  observed graph raises immediately, with the cycle in the message
  (catching the deadlock the one time the interleaving happens in a
  test, instead of hanging CI).
* **static consistency** — :func:`assert_consistent` checks the
  observed edges against PTL004's static graph: an observed edge A→B
  conflicts if the static graph can reach A from B (the two sides
  disagree about the global order).

Disarmed (the default), :func:`tracked` returns the lock unchanged —
zero overhead in production.
"""
from __future__ import annotations

import os
import threading

__all__ = ["enabled", "tracked", "TrackedLock", "observed_edges",
           "reset_edges", "assert_consistent", "LockOrderError"]


def enabled():
    return os.environ.get("PADDLE_TPU_LOCK_CHECKS", "0") not in ("", "0")


class LockOrderError(AssertionError):
    """An acquisition that closes a cycle in the observed lock graph."""


_STATE_GUARD = threading.Lock()
#: (held_label, acquired_label) -> count
_EDGES = {}
_TLS = threading.local()


def _held_stack():
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def observed_edges():
    """Copy of the observed acquisition-edge multiset."""
    with _STATE_GUARD:
        return dict(_EDGES)


def reset_edges():
    with _STATE_GUARD:
        _EDGES.clear()


def _record(held, acquired):
    from .locks import find_cycle
    with _STATE_GUARD:
        key = (held, acquired)
        fresh = key not in _EDGES
        _EDGES[key] = _EDGES.get(key, 0) + 1
        if fresh:
            cycle = find_cycle(set(_EDGES))
            if cycle:
                del _EDGES[key]
                raise LockOrderError(
                    f"acquiring {acquired!r} while holding {held!r} "
                    f"closes a lock-order cycle: {' -> '.join(cycle)}")


class TrackedLock:
    """A lock proxy that reports acquisition edges to the watchdog.

    Wraps Lock and RLock alike; re-entrant re-acquisition of the SAME
    label records no self-edge (RLock semantics are not an ordering
    hazard)."""

    def __init__(self, lock, name):
        self._lock = lock
        self.name = name

    def acquire(self, *a, **k):
        got = self._lock.acquire(*a, **k)
        if got:
            stack = _held_stack()
            if stack and stack[-1] != self.name:
                try:
                    _record(stack[-1], self.name)
                except LockOrderError:
                    # don't leak the just-acquired inner lock through
                    # the cycle error — the caller never saw it held
                    self._lock.release()
                    raise
            stack.append(self.name)
        return got

    def release(self):
        stack = _held_stack()
        # remove the most recent entry for this label (locks may be
        # released out of LIFO order; the stack is best-effort there)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()


def tracked(lock, name):
    """Wrap ``lock`` for edge recording when the watchdog is armed;
    return it unchanged otherwise."""
    if not enabled():
        return lock
    return TrackedLock(lock, name)


def assert_consistent(static_edges, observed=None):
    """Assert the observed runtime edges don't contradict the static
    lock-order graph: for every observed A→B, the static graph must not
    order B before A (reach A from B). Returns the list of observed
    edges that are NEW (absent from the static graph but consistent
    with it) — informational, since call-through acquisitions are
    invisible to the lexical scan."""
    static = set(static_edges)
    reach = {}

    def reachable(src, dst):
        if src not in reach:
            seen, frontier = set(), [src]
            while frontier:
                n = frontier.pop()
                for a, b in static:
                    if a == n and b not in seen:
                        seen.add(b)
                        frontier.append(b)
            reach[src] = seen
        return dst in reach[src]

    novel = []
    for a, b in (observed if observed is not None else observed_edges()):
        if (a, b) in static:
            continue
        if reachable(b, a):
            raise LockOrderError(
                f"runtime acquisition edge {a!r} -> {b!r} contradicts "
                f"the static lock-order graph (which orders {b!r} "
                f"before {a!r})")
        novel.append((a, b))
    return novel
