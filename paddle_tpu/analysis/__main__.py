"""CLI driver: ``python -m paddle_tpu.analysis [paths ...]``.

Exit code 0 iff every finding is suppressed inline or grandfathered in
the baseline — the contract ``tests/test_analysis_clean.py`` holds
tier-1 to."""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import load_baseline, run_analysis


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="paddle_tpu project-specific static checks "
                    "(PTL001-PTL007)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze "
                         "(default: ./paddle_tpu)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--all", action="store_true",
                    help="also print suppressed/baselined findings")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: ./analysis_baseline"
                         ".json when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline (report the raw state)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to grandfather every "
                         "current unsuppressed finding, then exit 0")
    args = ap.parse_args(argv)

    paths = args.paths or None
    if not paths:
        if os.path.isdir("paddle_tpu"):
            paths = ["paddle_tpu"]
        else:
            ap.error("no paths given and ./paddle_tpu does not exist")

    baseline = None
    baseline_path = args.baseline
    if baseline_path is None and os.path.exists("analysis_baseline.json"):
        baseline_path = "analysis_baseline.json"
    if baseline_path and not args.no_baseline and not args.write_baseline:
        baseline = load_baseline(baseline_path)

    report = run_analysis(paths, baseline=baseline)

    if args.write_baseline:
        out = baseline_path or "analysis_baseline.json"
        with open(out, "w") as fh:
            json.dump(report.baseline_json(), fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {out}: "
              f"{sum(report.baseline_json()['fingerprints'].values())} "
              f"grandfathered findings")
        return 0

    if args.as_json:
        json.dump(report.to_json(), sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        print(report.render(show_all=args.all))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
