"""PTL001 — implicit device→host sync detector for serving hot paths.

PR 8's headline win was structural: the fused all-decode stride pays
exactly ONE device→host sync per ``readout_stride`` tokens, and every
other host touch of device state in the dispatch→readout window shows
up straight in p99 inter-token latency. Nothing in Python stops the
next feature from dropping an ``int(self._lens[b])`` into
``step_begin`` — it works, it is just 10x the sync budget. This check
makes that a lint error.

Scope: functions whose NAME is one of the engine/serving hot-path
entry points (``step_begin``/``step_finish``/the fused walk/multi-step
scheduling/readout/gauge sampling). Nested ``def``s inside a hot
function are NOT scanned — in this codebase those are jit program
bodies (device-side, where ``int()`` is a trace-time cast, not a
sync).

Flagged patterns (each only when the expression *mentions device
state* — an attribute/name from the engine's device-buffer vocabulary,
or any ``jax.*``/``jnp.*`` call):

* ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` — always
  flagged, device-state mention or not (they are syncs by definition
  on anything jax-shaped).
* ``jax.device_get(...)`` / ``jax.block_until_ready(...)``.
* ``np.asarray(...)`` / ``np.array(...)`` — THE implicit D2H.
* ``int(...)`` / ``float(...)`` / ``bool(...)`` — scalar pulls.
* ``for _ in <device state>`` — iterating a jax array is one sync per
  element.

Documented readout sites — the one place per engine where the stride's
single sync is SUPPOSED to happen — are allowlisted by (path suffix,
function, snippet substring) in :data:`ALLOWED_SYNCS`; anything else
deliberate carries an inline ``# ptlint: disable=PTL001 -- reason``.
"""
from __future__ import annotations

import ast

from .core import Check

__all__ = ["HostSyncCheck", "HOT_FUNCTIONS", "ALLOWED_SYNCS"]

#: the engine/serving hot-path functions this check patrols. A name
#: match anywhere makes fixtures (and future engines speaking the step
#: protocol) patrol the same contract without a config edit.
HOT_FUNCTIONS = frozenset({
    # engine step protocol + fused scheduler walk
    "step_begin", "_step_begin_impl", "step_finish",
    "_begin_mixed_step", "_begin_spec_decode", "_schedule_mixed",
    "_admit_waiting", "_admit_fused", "_record_dispatch",
    # serving loop: dispatch/readout wrappers, gauge sampling,
    # telemetry stamping
    "_serve_loop", "_begin_step", "_finish_step", "_update_gauges",
    "_feed_engine", "_on_token", "_note_admissions",
    "_sweep_cancels_and_deadlines", "_handle_done",
})

#: attribute names that ARE device state in this codebase (engine
#: buffers and PendingStep futures) — an expression touching one of
#: these inside a hot function is a device touch.
DEVICE_ATTRS = frozenset({
    "_lens", "_logits", "_k", "_v", "_tokens", "_rng_key", "_state_vals",
    "toks", "counts", "was_active", "offered", "pooled", "out",
})

#: bare names treated as device state (locals conventionally bound to
#: dispatch outputs before the readout).
DEVICE_NAMES = frozenset({"toks", "counts", "was_active", "offered",
                          "pooled", "logits"})

#: (path suffix, function, snippet substring) triples naming the
#: DOCUMENTED readout sites — the one sync per stride each engine is
#: contractually allowed. The anchor is the specific readout FORM
#: (materializing this dispatch's device futures), not the pending
#: object: a future `int(pending.counts[b])` scalar pull in the same
#: function still fires. Everything else needs an inline suppression
#: with a reason.
ALLOWED_SYNCS = (
    ("inference/llm_engine.py", "step_finish", "np.asarray(pending."),
    ("serving/embedding.py", "step_finish", "np.asarray(pending."),
)

_SYNC_METHODS = ("item", "tolist", "block_until_ready")
_CAST_FUNCS = ("int", "float", "bool")
_NP_FUNCS = ("asarray", "array")


def _mentions_device(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in DEVICE_ATTRS:
            return True
        if isinstance(sub, ast.Name) and sub.id in DEVICE_NAMES:
            return True
        if isinstance(sub, ast.Call):
            root = sub.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in ("jax", "jnp"):
                return True
    return False


class HostSyncCheck(Check):
    id = "PTL001"
    describe = ("implicit device->host sync inside an engine/serving "
                "hot path (one sync per stride is the contract)")

    def run(self, mod):
        # textual prefilter: most modules define no hot-path function
        if not any(name in mod.text for name in HOT_FUNCTIONS):
            return
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in HOT_FUNCTIONS:
                yield from self._scan_hot(mod, node)

    def _allowed(self, mod, func, node):
        seg = mod.segment(node)
        for suffix, fn, sub in ALLOWED_SYNCS:
            if mod.relpath.endswith(suffix) and func == fn and sub in seg:
                return True
        return False

    def _scan_hot(self, mod, fn):
        # walk the hot function body but never descend into nested defs
        # (jit program bodies are device-side; a lambda/callback is not
        # this function's sync budget)
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            hits = list(self._scan_node(mod, fn.name, node))
            for f in hits:
                if not self._allowed(mod, fn.name, f[0]):
                    yield self.finding(mod, f[0], f[1], func=fn.name)
            if not hits:
                stack.extend(ast.iter_child_nodes(node))
                continue
            # one finding per sync EXPRESSION: don't re-flag nested
            # parts of an already-reported (or allowlisted) sync like
            # `int(pending.counts[0].item())` — but keep scanning
            # sibling subtrees (a flagged `for ... in self.toks:` must
            # not exempt the syncs inside its body)
            skip = set()
            for anchor, _ in hits:
                for sub in ast.walk(anchor):
                    skip.add(id(sub))
            stack.extend(c for c in ast.iter_child_nodes(node)
                         if id(c) not in skip)

    def _scan_node(self, mod, func, node):
        if isinstance(node, ast.For) and _mentions_device(node.iter):
            yield (node.iter,
                   f"iterating device state "
                   f"`{mod.segment(node.iter)}` syncs once per element")
            return
        if not isinstance(node, ast.Call):
            return
        callee = node.func
        if isinstance(callee, ast.Attribute):
            if callee.attr in _SYNC_METHODS:
                yield (node, f"`.{callee.attr}()` forces a device->host "
                             f"sync: `{mod.segment(node)}`")
                return
            root = callee.value
            if isinstance(root, ast.Name):
                if root.id == "jax" and callee.attr in (
                        "device_get", "block_until_ready",
                        "effects_barrier"):
                    yield (node, f"`jax.{callee.attr}` syncs the host: "
                                 f"`{mod.segment(node)}`")
                    return
                if root.id in ("np", "numpy") and \
                        callee.attr in _NP_FUNCS and node.args and \
                        _mentions_device(node.args[0]):
                    yield (node, f"`np.{callee.attr}` of device state is "
                                 f"an implicit D2H sync: "
                                 f"`{mod.segment(node)}`")
                    return
        elif isinstance(callee, ast.Name) and callee.id in _CAST_FUNCS \
                and node.args and _mentions_device(node.args[0]):
            yield (node, f"`{callee.id}()` of device state is a scalar "
                         f"device->host pull: `{mod.segment(node)}`")
