"""PTL004 — lock-discipline pass + static lock-acquisition-order graph.

The serving stack's concurrency contract (PR 5/8/9) is narrow and
documented, which makes it checkable:

* The **paged-pool allocator** (free heap, prefix LRU, quarantine,
  refcounts, block tables, write fences), the **content store** and the
  **adapter device cache** are mutated ONLY from engine-thread methods
  — ``LLMEngine``/``BertEmbedEngine``/``AdapterDeviceCache`` bodies.
  There is deliberately no lock on that state; a mutation reached from
  anywhere else is a race, full stop.
* Cross-thread state (server handle table, router replica table,
  adapter registry) is mutated only under its documented lock
  (``_hlock`` / ``_lock`` / ``_dispatch_lock``).

This pass flags protected-state mutations outside both shelters, and
builds the **static lock-acquisition-order graph** from lexically
nested ``with <lock>:`` blocks: an edge A→B means "B acquired while
holding A". A cycle in that graph is a deadlock waiting for the right
interleaving — reported as an error finding. The runtime watchdog
(:mod:`paddle_tpu.analysis.lock_watchdog`, armed by
``PADDLE_TPU_LOCK_CHECKS=1``) records the edges that actually happen —
including through calls, which no lexical scan can see — and asserts
them against this graph.
"""
from __future__ import annotations

import ast

from .core import Check, Finding

__all__ = ["LockDisciplineCheck", "PROTECTED_ATTRS", "ENGINE_OWNERS",
           "DOCUMENTED_LOCKS", "static_lock_graph", "find_cycle"]

#: protected attribute -> what it is (the engine-thread-owned and
#: lock-guarded state PR 5/7/8/9 built their invariants on)
PROTECTED_ATTRS = {
    "_free_blocks": "paged-pool free heap",
    "_lru": "prefix-cache / adapter LRU",
    "_quarantine": "fenced-block quarantine",
    "_block_ref": "pool refcounts",
    "_block_hash": "content-store hashes",
    "_block_tokens": "content-store tokens",
    "_slot_blocks": "slot block lists",
    "_tables": "block tables",
    "_write_fence": "in-flight write fence",
    "_slot_of": "adapter cache slot map",
    "_slot_aid": "adapter cache slot owners",
    "_ref": "adapter cache refcounts",
    "_free": "adapter cache free list",
    "_adapters": "adapter registry",
    "_handles": "server handle table",
}

#: classes whose methods ARE the engine thread (by the step-protocol
#: contract): mutations inside them need no lock.
ENGINE_OWNERS = frozenset({"LLMEngine", "BertEmbedEngine",
                           "AdapterDeviceCache"})

#: the documented lock attributes of the serving stack
DOCUMENTED_LOCKS = frozenset({"_hlock", "_lock", "_dispatch_lock",
                              "_plock"})

#: methods whose call on a protected attribute mutates it
_MUTATORS = frozenset({"add", "append", "appendleft", "pop", "popleft",
                       "popitem", "remove", "discard", "clear", "update",
                       "setdefault", "extend", "insert"})

#: functions allowed to (re)build protected state wholesale
_INIT_FUNCS = frozenset({"__init__", "reset", "_init_device_state"})


def _protected_attr(node):
    """The protected attribute name accessed by ``node`` (an Attribute
    or a Subscript/chain rooted in one), or None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in PROTECTED_ATTRS:
        return node.attr
    return None


def _lock_label(expr, cls):
    """'Class._lockattr' for ``with self._lockattr:`` style nodes, or
    None when the with-item is not lock-shaped."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return None
    if name in DOCUMENTED_LOCKS or "lock" in name.lower():
        return f"{cls or '<module>'}.{name}"
    return None


def find_cycle(edges):
    """One cycle in a directed edge set as a node list, or None."""
    graph = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack = []

    def dfs(n):
        color[n] = GRAY
        stack.append(n)
        for nxt in sorted(graph.get(n, ())):
            if color.get(nxt, WHITE) == GRAY:
                return stack[stack.index(nxt):] + [nxt]
            if color.get(nxt, WHITE) == WHITE:
                cyc = dfs(nxt)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


class LockDisciplineCheck(Check):
    id = "PTL004"
    describe = ("allocator/content-store/adapter-cache mutations outside "
                "engine-thread methods or documented locks; lock-order "
                "cycles")

    def __init__(self):
        #: (lock_a, lock_b) -> (relpath, line) where b was first seen
        #: acquired while holding a
        self.edges = {}

    # -- per-module ------------------------------------------------------
    def run(self, mod):
        # textual prefilter: nothing protected and nothing lock-shaped
        if "lock" not in mod.text.lower() and \
                not any(a in mod.text for a in PROTECTED_ATTRS):
            return
        yield from self._walk(mod, mod.tree, cls=None, func=None,
                              held=(), guarded=False)

    def _walk(self, mod, node, cls, func, held, guarded):
        for child in ast.iter_child_nodes(node):
            c_cls, c_func, c_held, c_guarded = cls, func, held, guarded
            if isinstance(child, ast.ClassDef):
                c_cls = child.name
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                c_func = child.name
                c_held, c_guarded = (), False     # locks don't cross defs
            elif isinstance(child, ast.With):
                for item in child.items:
                    label = _lock_label(item.context_expr, c_cls)
                    if label is None:
                        continue
                    # extend held BEFORE the next item so `with A, B:`
                    # records the A->B edge exactly like nested withs
                    # (CPython acquires multi-item withs left to right)
                    for h in c_held:
                        if h != label and (h, label) not in self.edges:
                            self.edges[(h, label)] = (mod.relpath,
                                                      child.lineno)
                    c_held = c_held + (label,)
                    c_guarded = True
            else:
                yield from self._check_mutation(mod, child, c_cls, c_func,
                                                c_guarded)
            yield from self._walk(mod, child, c_cls, c_func, c_held,
                                  c_guarded)

    def _check_mutation(self, mod, node, cls, func, guarded):
        attr = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = attr or _protected_attr(t)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = attr or _protected_attr(t)
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                attr = _protected_attr(node.func.value)
            else:
                # heapq.heappush(self._free_blocks, x) and friends
                chain_root = node.func
                while isinstance(chain_root, ast.Attribute):
                    chain_root = chain_root.value
                if isinstance(chain_root, ast.Name) and \
                        chain_root.id == "heapq" and node.args:
                    attr = _protected_attr(node.args[0])
        if attr is None:
            return
        if cls in ENGINE_OWNERS or guarded:
            return
        if func in _INIT_FUNCS:
            return
        where = f"{cls}.{func}" if cls and func else (func or cls or
                                                      "<module>")
        yield self.finding(
            mod, node,
            f"mutation of {PROTECTED_ATTRS[attr]} (`{attr}`) in "
            f"`{where}` — outside engine-thread owner classes "
            f"({', '.join(sorted(ENGINE_OWNERS))}) and not under a "
            f"documented lock",
            key=f"unguarded:{where}:{attr}", func=func or "<module>")

    # -- cross-module ----------------------------------------------------
    def finalize(self):
        cycle = find_cycle(set(self.edges))
        if cycle:
            a, b = cycle[0], cycle[1]
            path, line = self.edges.get((a, b), ("(lock-order graph)", 0))
            yield Finding(
                self.id, path, line, 0, "<lock-order-graph>",
                f"lock-acquisition-order cycle: {' -> '.join(cycle)} — "
                f"a deadlock under the right interleaving",
                key=f"lock-cycle:{'->'.join(sorted(set(cycle)))}")

    def lock_graph_json(self):
        return {
            "edges": [{"from": a, "to": b, "path": p, "line": ln}
                      for (a, b), (p, ln) in sorted(self.edges.items())],
            "cycle": find_cycle(set(self.edges)) or []}


def static_lock_graph(paths):
    """The static lock-order edge set of ``paths`` — the runtime
    watchdog's reference. Returns ``{(lock_a, lock_b): (path, line)}``."""
    from .core import run_analysis
    check = LockDisciplineCheck()
    run_analysis(paths, checks=[check])
    return dict(check.edges)
