"""PTL006 — device↔host KV-pool copies outside the fence-tracked swap
API.

The host KV tier (``LLMEngine(kv_host_swap=..., kv_host_spill_bytes=
...)``) moves pool blocks between device HBM and host RAM through
exactly four functions — ``_swap_out_slot`` / ``_spill_block`` (D2H)
and ``_try_swap_restores`` / ``_promote_spilled`` (H2D) — and the
cross-replica ship path (PR 17) adds four more on the same fences:
``_export_slot_kv`` / ``export_prefix_blocks`` (D2H staging for a ship)
and the transport's ``serialize_entry`` / ``deserialize_entry`` (wire
encode/decode over the staged, already-booked buffers). Those functions
are where the correctness obligations live: the gather must take the
engine's NEWEST pool futures (so it sequences after every in-flight
writer), the scatter must target freshly allocated blocks the write
fence keeps out of every in-flight dispatch, and each direction books
its bytes/blocks on the ``kv_swap_*`` / ``kv_ship_*`` stats the
StepRecord split and the preemption A/B read.

A KV copy issued anywhere else has none of those guarantees: it can
race a pipelined writer (silently on CPU, corrupt KV on TPU), and its
bytes vanish from the swap accounting — the bench's "re-prefill tokens
avoided" number quietly lies. This check makes that a lint error:

* any ``np.asarray`` / ``np.array`` / ``jax.device_get`` /
  ``jax.device_put`` / ``.copy_to_host_async()`` call whose argument
  expression touches a KV pool (``self._k`` / ``self._v``, or the
  conventional pool parameter names ``k_pools``/``v_pools``/
  ``k_bufs``/``v_bufs``), and
* any call of the compiled tier programs themselves
  (``_kv_gather_fn`` / ``_kv_scatter_fn``) — the tracked API boundary,

outside the allowlisted swap-API functions, is flagged. Deliberate
exceptions carry ``# ptlint: disable=PTL006 -- reason`` like every
other check.
"""
from __future__ import annotations

import ast

from .core import Check

__all__ = ["KVTransferCheck", "KV_POOL_ATTRS", "KV_POOL_NAMES",
           "SWAP_PROGRAMS", "ALLOWED_TRANSFER_FUNCS"]

#: attribute names that ARE the paged KV pools in this codebase
KV_POOL_ATTRS = frozenset({"_k", "_v"})

#: conventional parameter/local names bound to the pools (the jit
#: program bodies and staging helpers)
KV_POOL_NAMES = frozenset({"k_pools", "v_pools", "k_bufs", "v_bufs"})

#: the compiled tier programs — calling one IS a device↔host KV
#: transfer commitment, wherever the bytes end up
SWAP_PROGRAMS = frozenset({"_kv_gather_fn", "_kv_scatter_fn"})

#: (path suffix, function) pairs naming THE fence-tracked transfer API —
#: the only places a KV-pool transfer may be issued: the host-tier swap
#: halves, the cross-replica ship staging points (same gather, entries
#: book on kv_ship_* instead), and the transport's wire encode/decode
#: (which materializes pool-derived leaf buffers). Kept in sync with
#: the source files by tests/test_analysis_clean.py (a rename there
#: makes the repo scan light up here).
ALLOWED_TRANSFER_FUNCS = (
    ("inference/llm_engine.py", "_swap_out_slot"),
    ("inference/llm_engine.py", "_try_swap_restores"),
    ("inference/llm_engine.py", "_spill_block"),
    ("inference/llm_engine.py", "_promote_spilled"),
    ("inference/llm_engine.py", "_export_slot_kv"),
    ("inference/llm_engine.py", "export_prefix_blocks"),
    ("serving/kv_transport.py", "serialize_entry"),
    ("serving/kv_transport.py", "deserialize_entry"),
)

_TRANSFER_FUNCS = {("jax", "device_get"), ("jax", "device_put"),
                   ("np", "asarray"), ("np", "array"),
                   ("numpy", "asarray"), ("numpy", "array")}


def _touches_pool(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in KV_POOL_ATTRS:
            return True
        if isinstance(sub, ast.Name) and sub.id in KV_POOL_NAMES:
            return True
    return False


def _classify_call(node):
    """(label, needs_pool_mention) for a transfer-shaped call, else
    None."""
    callee = node.func
    if isinstance(callee, ast.Attribute):
        if callee.attr == "copy_to_host_async":
            return ".copy_to_host_async()", True
        if callee.attr in SWAP_PROGRAMS:
            return f"self.{callee.attr}(...)", False
        root = callee.value
        if isinstance(root, ast.Name) and \
                (root.id, callee.attr) in _TRANSFER_FUNCS:
            return f"{root.id}.{callee.attr}", True
    return None


class KVTransferCheck(Check):
    id = "PTL006"
    describe = ("device<->host KV-pool copy outside the fence-tracked "
                "swap API (races in-flight writers, skips the swap "
                "accounting)")

    def run(self, mod):
        # textual prefilter: a module with no transfer-shaped call and
        # no tier-program reference cannot fire
        if not any(tok in mod.text for tok in
                   ("copy_to_host_async", "device_get", "device_put",
                    "asarray", "np.array", "numpy.array",
                    "_kv_gather_fn", "_kv_scatter_fn")):
            return
        yield from self._scan_scope(mod, mod.tree, "<module>")
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan_scope(mod, node, node.name)

    def _allowed(self, mod, func):
        return any(mod.relpath.endswith(suffix) and func == fn
                   for suffix, fn in ALLOWED_TRANSFER_FUNCS)

    def _scan_scope(self, mod, scope, func):
        if self._allowed(mod, func):
            return
        # scan this scope's body without descending into nested defs —
        # each nested function is judged under its OWN name (a helper
        # inside an allowed function is not itself allowed; an allowed
        # function nested in a disallowed one still is)
        stack = list(scope.body if hasattr(scope, "body") else [])
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                hit = _classify_call(node)
                if hit is not None:
                    label, needs_pool = hit
                    if not needs_pool or _touches_pool(node):
                        yield self.finding(
                            mod, node,
                            f"`{label}` moves KV-pool bytes across the "
                            f"device boundary outside the fence-tracked "
                            f"transfer API (the swap halves "
                            f"_swap_out_slot/_try_swap_restores/"
                            f"_spill_block/_promote_spilled, the ship "
                            f"stagers _export_slot_kv/"
                            f"export_prefix_blocks, and the transport "
                            f"serialize_entry/deserialize_entry) — it "
                            f"can race an in-flight writer and its "
                            f"bytes skip the kv_swap_*/kv_ship_* "
                            f"accounting",
                            key=f"kv-transfer:{label}", func=func)
                        continue     # one finding per transfer call
            stack.extend(ast.iter_child_nodes(node))
