"""``paddle_tpu.analysis`` — project-specific static checkers + runtime
sanitizers for the invariants the serving stack's performance rests on.

The last several PRs bought their wins by enforcing source-level
disciplines — one D2H sync per readout stride, donation-consumed
buffers rebuilt only via ``reset()``, allocator mutations confined to
the engine thread, strict telemetry names. This package encodes those
as AST-level checks so the NEXT change to a hot path fails lint, not a
p99 bench three rounds later:

==========  =========================================================
 PTL000      ``ptlint: disable`` suppression without a reason string
 PTL001      implicit device→host sync in an engine/serving hot path
 PTL002      retrace/concretization hazards reaching ``jax.jit``
 PTL003      donated buffer read after the donating call
 PTL004      unguarded allocator/cache mutations + lock-order cycles
 PTL005      telemetry names missing from the ServingTelemetry registry
 PTL006      device↔host KV-pool copy outside the fence-tracked swap API
 PTL007      SLO/pathology names missing from the ALERT_KINDS /
             LABELED_GAUGE_FAMILIES registries
 PTL008      tracing names (request-event kinds, trace-hop vias,
             Perfetto counter/flow tracks, tail causes) off their
             flight-recorder/types registries
==========  =========================================================

CLI::

    python -m paddle_tpu.analysis [paths ...] [--json] [--all]
        [--baseline analysis_baseline.json] [--write-baseline]

Per-line suppression: ``# ptlint: disable=PTL001 -- reason`` (the
reason is mandatory — PTL000 flags bare suppressions). Grandfathered
findings live in the checked-in ``analysis_baseline.json``;
``tests/test_analysis_clean.py`` keeps the repo finding-free modulo
that baseline in tier-1.

Runtime sanitizers (the dynamic halves):

* transfer-guard window — ``PADDLE_TPU_TRANSFER_CHECKS=1`` (armed by
  the test conftest) makes the engine hold
  ``jax.transfer_guard("disallow")`` across the fused all-decode
  stride's dispatch→readout window and counts the documented readout
  as ``stats["guarded_syncs"]`` — the one-sync-per-stride contract as
  an assertion instead of a bench number.
* lock-order watchdog — ``PADDLE_TPU_LOCK_CHECKS=1`` wraps the
  documented serving locks, records actual acquisition edges, raises
  on cycles online, and :func:`lock_watchdog.assert_consistent` checks
  the observed edges against PTL004's static graph.
"""
from .core import (Finding, Report, JSON_SCHEMA_VERSION, default_checks,
                   iter_py_files, load_baseline, run_analysis)
from .locks import static_lock_graph
from . import lock_watchdog

__all__ = ["Finding", "Report", "JSON_SCHEMA_VERSION", "default_checks",
           "iter_py_files", "load_baseline", "run_analysis",
           "static_lock_graph", "lock_watchdog", "count_findings"]


def count_findings(paths, baseline_path=None):
    """Convenience for bench/CI headers: ``(active, baselined,
    suppressed)`` finding counts for ``paths``. ``active`` is what
    would fail the run; ``baselined`` is the grandfathered debt still
    to burn down."""
    baseline = None
    if baseline_path is not None:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError):
            baseline = None
    report = run_analysis(paths, baseline=baseline)
    s = report.summary()
    return s["new"], s["baselined"], s["suppressed"]
