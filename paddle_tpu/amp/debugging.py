"""Numeric debugging tools (reference: python/paddle/amp/debugging.py —
TensorCheckerConfig, enable_tensor_checker, operator stats collection,
accuracy comparison; kernel twin: phi/kernels/check_numerics_kernel.h).

TPU-native: the nan/inf sanitizer rides the dispatch-level check_nan_inf flag
(core/tensor.py), and operator stats ride the _OP_OBSERVERS dispatch hook —
no per-kernel instrumentation needed since every op funnels through dispatch.
"""
from __future__ import annotations

import contextlib
from enum import Enum

import numpy as np
import jax
import jax.numpy as jnp

from ..core.flags import set_flags, get_flags
from ..core import tensor as _tensor_mod
from ..core.tensor import Tensor

__all__ = [
    "DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
    "disable_tensor_checker", "check_numerics", "enable_operator_stats_collection",
    "disable_operator_stats_collection", "collect_operator_stats",
    "compare_accuracy",
]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


class TensorCheckerConfig:
    """Reference: amp/debugging.py TensorCheckerConfig."""

    def __init__(self, enable, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list
        self.debug_step = debug_step

    def _level(self):
        return 0 if self.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT else 1


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    if checker_config.enable:
        set_flags({"check_nan_inf": True,
                   "check_nan_inf_level": checker_config._level()})


def disable_tensor_checker():
    set_flags({"check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Count nan/inf/zero and min/max/mean of one tensor (reference:
    amp/debugging.py check_numerics -> check_numerics kernel).

    Returns (stats, values): stats = [num_nan, num_inf, num_zero] int64 Tensor,
    values = [max, min, mean] float32 Tensor.

    Host-resident (numpy) tensors audit through the native multithreaded scanner
    (csrc/numeric.cc — the FLAGS_check_nan_inf host path); device arrays audit
    on-device so no transfer is forced."""
    import numpy as _np
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if isinstance(v, _np.ndarray):
        from ..core.native import scan_array
        r = scan_array(v)
        if r is not None:
            stats = _np.asarray([r["nan_count"], r["inf_count"],
                                 r["zero_count"]], dtype=_np.int64)
            nf = r["finite_count"]
            values = _np.asarray(
                [r["max"] if nf else _np.nan,
                 r["min"] if nf else _np.nan,
                 (r["sum"] / nf) if nf else _np.nan], dtype=_np.float32)
            return Tensor(stats, stop_gradient=True), Tensor(values,
                                                             stop_gradient=True)
    vf = v.astype(jnp.float32)
    finite = jnp.isfinite(vf)
    stats = jnp.stack([jnp.sum(jnp.isnan(vf)).astype(jnp.int64),
                       jnp.sum(jnp.isinf(vf)).astype(jnp.int64),
                       jnp.sum(vf == 0).astype(jnp.int64)])
    safe = jnp.where(finite, vf, jnp.nan)
    values = jnp.stack([jnp.nanmax(safe), jnp.nanmin(safe), jnp.nanmean(safe)])
    return Tensor(stats, stop_gradient=True), Tensor(values, stop_gradient=True)


class _OpStatsCollector:
    def __init__(self):
        self.stats = {}

    def __call__(self, name, leaves):
        for v in leaves:
            if not hasattr(v, "dtype"):
                continue
            key = f"{name}-{np.dtype(v.dtype).name}"
            ent = self.stats.setdefault(key, {"calls": 0, "num_nan": 0,
                                              "num_inf": 0})
            ent["calls"] += 1
            if (jnp.issubdtype(v.dtype, jnp.inexact)
                    and not isinstance(v, jax.core.Tracer)):
                # tracers (ops inside a jit trace) are counted but not
                # inspected — forcing them concrete would abort the trace
                ent["num_nan"] += int(jnp.sum(jnp.isnan(v)))
                ent["num_inf"] += int(jnp.sum(jnp.isinf(v)))


_ACTIVE: list[_OpStatsCollector] = []


def enable_operator_stats_collection():
    """Start collecting per-op call/nan/inf stats (reference:
    amp/debugging.py enable_operator_stats_collection)."""
    c = _OpStatsCollector()
    _ACTIVE.append(c)
    _tensor_mod._OP_OBSERVERS.append(c)


def disable_operator_stats_collection():
    if not _ACTIVE:
        return
    c = _ACTIVE.pop()
    _tensor_mod._OP_OBSERVERS.remove(c)
    _print_operator_stats(c.stats)


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def _print_operator_stats(stats):
    print(f"{'op-dtype':<48} {'calls':>8} {'nan':>8} {'inf':>8}")
    for key in sorted(stats):
        s = stats[key]
        print(f"{key:<48} {s['calls']:>8} {s['num_nan']:>8} {s['num_inf']:>8}")


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Diff two operator-stats .npz dumps (reference: amp/debugging.py
    compare_accuracy over check_nan_inf dump dirs); writes a CSV report."""
    import csv
    a = np.load(dump_path, allow_pickle=True)
    b = np.load(another_dump_path, allow_pickle=True)
    keys = sorted(set(a.files) | set(b.files))
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["tensor", "max_abs_diff", "mean_abs_diff", "shape_a",
                    "shape_b"])
        for k in keys:
            if k not in a.files or k not in b.files:
                w.writerow([k, "missing", "", k in a.files, k in b.files])
                continue
            va, vb = np.asarray(a[k], np.float64), np.asarray(b[k], np.float64)
            if va.shape != vb.shape:
                w.writerow([k, "shape-mismatch", "", va.shape, vb.shape])
                continue
            d = np.abs(va - vb)
            w.writerow([k, float(d.max()), float(d.mean()), va.shape, vb.shape])
    return output_filename


def check_layer_numerics(func):
    """Decorator: audit a Layer.forward's inputs/outputs for nan/inf
    (reference: amp/debugging.py check_layer_numerics — wraps forward with
    per-tensor numeric checks)."""
    import functools

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        for i, a in enumerate(args):
            if hasattr(a, "_value"):
                check_numerics(a, op_type=type(self).__name__,
                               var_name=f"input_{i}")
        out = func(self, *args, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for i, o in enumerate(outs):
            if hasattr(o, "_value"):
                check_numerics(o, op_type=type(self).__name__,
                               var_name=f"output_{i}")
        return out
    return wrapper


__all__.append("check_layer_numerics")
