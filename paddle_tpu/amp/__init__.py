"""AMP — automatic mixed precision (paddle.amp analog).

Reference: python/paddle/amp/auto_cast.py:102 (AMPGlobalState injected in every
generated ad_func), amp_lists.py, grad_scaler.py:62. TPU-native: bf16 is the native
matmul dtype, so O1 autocast = cast white-listed op inputs to bf16 at dispatch time
(an op-dispatch hook, same injection point as the reference); loss scaling is rarely
needed for bf16 but GradScaler is provided for fp16 parity.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, dispatch, no_grad

# ops cast to low precision under O1 (matmul-class: MXU-bound)
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "flash_attention", "flash_attention_dropout",
    "scaled_dot_product_attention",
}
# ops kept in fp32 (numerically sensitive)
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "softmax", "log_softmax",
    # cross_entropy / softmax_with_cross_entropy are NOT black-listed: the
    # fused CE kernel accumulates its lse in fp32 internally, and an O1
    # upcast here would materialize the (tokens, vocab) fp32 logits copy the
    # kernel exists to avoid
    "nll_loss", "layer_norm", "batch_norm", "group_norm",
    "rms_norm", "mean", "sum", "logsumexp",
    "cosine_similarity", "erf", "erfinv", "pow", "rsqrt",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = dtypes.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()

from ..core.tensor import install_amp_hook as _install  # noqa: E402

def _hook(name, vals):
    return amp_cast_inputs(name, vals)

_install(_hook)


def amp_state():
    return _state


def policy_fingerprint():
    """Hashable snapshot of the active autocast policy — part of every
    compiled-program cache key (a program traced under one policy bakes
    its casts in; reusing it under another would silently change dtypes)."""
    if not _state.enabled:
        return None
    return (str(_state.dtype), _state.level,
            frozenset(_state.custom_white), frozenset(_state.custom_black))


def amp_cast_inputs(name: str, leaves: list):
    """dispatch() hook: cast tensor-value leaves per AMP policy. Returns new list."""
    if not _state.enabled:
        return leaves
    white = (WHITE_LIST | _state.custom_white) - _state.custom_black
    black = (BLACK_LIST | _state.custom_black) - _state.custom_white
    lo = _state.dtype

    def cast_to(v, d):
        if isinstance(v, jax.Array) and jnp.issubdtype(v.dtype, jnp.floating) \
                and v.dtype != jnp.float64 and v.dtype != d:
            return v.astype(d)
        return v

    if name in white:
        return [cast_to(v, lo) for v in leaves]
    if name in black and _state.level == "O1":
        return [cast_to(v, jnp.float32) for v in leaves]
    return leaves


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16", use_promote=True):
    saved = (_state.enabled, _state.dtype, _state.level, _state.custom_white,
             _state.custom_black)
    _state.enabled = enable
    _state.dtype = dtypes.convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.custom_white,
         _state.custom_black) = saved


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to low precision; optimizer keeps fp32 masters."""
    d = dtypes.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.astype(d)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (reference: amp/grad_scaler.py:657 GradScaler)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable:
            return loss
        from .. import ops
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._value * inv
                p.grad._value = g
                found = found or bool(jnp.any(~jnp.isfinite(g)))
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._found_inf:
            self.unscale_(optimizer)
        if self._found_inf:
            self._cache_founds = True
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()
        optimizer.clear_grad()

    def update(self):
        if not self._dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        from .. import ops
        return ops.to_tensor(self._scale)

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._scale = d["scale"]
        self._good_steps = d["good_steps"]
        self._bad_steps = d["bad_steps"]


AmpScaler = GradScaler


def is_bfloat16_supported(place=None):
    return True


def is_float16_supported(place=None):
    return True


from . import debugging  # noqa: E402,F401  (paddle.amp.debugging parity)
