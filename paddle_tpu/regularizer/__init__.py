"""paddle.regularizer analog — weight-decay policies consumed by optimizers.

Reference: python/paddle/regularizer.py (L1Decay/L2Decay appended to the grad during
the optimizer update). The optimizer base reads ``_coeff`` / ``_kind`` and applies the
decay inside its jit'd update (optimizer/optimizer.py).
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]


class WeightDecayRegularizer:
    _kind = "none"

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """L1 weight decay: grad += coeff * sign(param)."""

    _kind = "l1"


class L2Decay(WeightDecayRegularizer):
    """L2 weight decay: grad += coeff * param (coupled decay)."""

    _kind = "l2"
