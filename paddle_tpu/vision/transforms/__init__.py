"""Minimal vision transforms (reference: python/paddle/vision/transforms/).

Numpy-based host-side preprocessing for DataLoader pipelines.
"""
from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
        hwc = arr.ndim == 3 and arr.shape[-1] in (1, 3, 4)
        if hwc:
            new_shape = (*self.size, arr.shape[-1])
        else:
            new_shape = (arr.shape[0], *self.size)
        out = np.asarray(jax.image.resize(jnp.asarray(arr), new_shape, "bilinear"))
        return Tensor(out) if isinstance(img, Tensor) else out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        h, w = arr.shape[-3:-1] if arr.shape[-1] in (1, 3, 4) else arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
            out = arr[i:i + th, j:j + tw, :]
        else:
            out = arr[..., i:i + th, j:j + tw]
        return Tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
                out = arr[:, ::-1].copy()
            else:
                out = arr[..., ::-1].copy()
            return Tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = not (arr.ndim == 3 and arr.shape[-1] in (1, 3, 4))
        h, w = arr.shape[-2:] if chw else arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        out = arr[..., i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw, :]
        return Tensor(out) if isinstance(img, Tensor) else out


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def _to_np(img):
    return img.numpy() if isinstance(img, Tensor) else np.asarray(img)


def _wrap_like(img, out):
    return Tensor(out) if isinstance(img, Tensor) else out


def _is_hwc(arr):
    return arr.ndim == 3 and arr.shape[-1] in (1, 3, 4)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = _to_np(img)
            out = arr[::-1].copy() if _is_hwc(arr) else arr[..., ::-1, :].copy()
            return _wrap_like(img, out)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4  # left, top, right, bottom
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.mode = {"constant": "constant", "edge": "edge",
                     "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]

    def _apply_image(self, img):
        arr = _to_np(img)
        l, t, r, b = self.padding
        if _is_hwc(arr):
            widths = [(t, b), (l, r), (0, 0)]
        else:
            widths = [(0, 0)] * (arr.ndim - 2) + [(t, b), (l, r)]
        kw = {"constant_values": self.fill} if self.mode == "constant" else {}
        return _wrap_like(img, np.pad(arr, widths, mode=self.mode, **kw))


class RandomResizedCrop(BaseTransform):
    """Crop a random area/aspect patch, resize to `size` (reference:
    transforms/transforms.py RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_np(img)
        hwc = _is_hwc(arr)
        h, w = (arr.shape[0], arr.shape[1]) if hwc else arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                break
        else:
            ch, cw = min(h, w), min(h, w)
            i, j = (h - ch) // 2, (w - cw) // 2
        patch = arr[i:i + ch, j:j + cw] if hwc else arr[..., i:i + ch, j:j + cw]
        return _wrap_like(img, np.asarray(
            Resize(self.size, self.interpolation)._apply_image(
                patch.astype(np.float32))))


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = _to_np(img).astype(np.float32)
        hwc = _is_hwc(arr)
        weights = np.asarray([0.299, 0.587, 0.114], np.float32)
        # luminance from the RGB channels; an alpha channel (RGBA) is dropped
        rgb = arr[..., :3] if hwc else arr[..., :3, :, :]
        if (rgb.shape[-1] if hwc else rgb.shape[-3]) == 1:
            gray = rgb
        elif hwc:
            gray = (rgb * weights[None, None, :]).sum(-1, keepdims=True)
        else:
            gray = (rgb * weights[:, None, None]).sum(-3, keepdims=True)
        reps = [1] * gray.ndim
        reps[-1 if hwc else -3] = self.num_output_channels
        return _wrap_like(img, np.tile(gray, reps))


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = _to_np(img).astype(np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return _wrap_like(img, arr * factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = _to_np(img).astype(np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = arr.mean()
        return _wrap_like(img, (arr - mean) * factor + mean)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = _to_np(img).astype(np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = np.asarray(Grayscale(3)._apply_image(arr))
        return _wrap_like(img, gray + factor * (arr - gray))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        assert 0 <= value <= 0.5
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = _to_np(img).astype(np.float32)
        hwc = _is_hwc(arr)
        x = arr if hwc else np.moveaxis(arr, -3, -1)
        scaled = x.max() > 1.5
        xf = x / 255.0 if scaled else x
        mx, mn = xf.max(-1), xf.min(-1)
        diff = mx - mn + 1e-10
        r, g, b = xf[..., 0], xf[..., 1], xf[..., 2]
        h = np.where(mx == r, (g - b) / diff % 6,
                     np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
        h = h / 6.0
        shift = np.random.uniform(-self.value, self.value)
        h = (h + shift) % 1.0
        s = np.where(mx > 0, diff / (mx + 1e-10), 0)
        v = mx
        i = np.floor(h * 6.0)
        f = h * 6.0 - i
        p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
        i = (i.astype(int) % 6)[..., None]  # broadcast over the channel dim
        out = np.select(
            [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
            [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
             np.stack([p, v, t], -1), np.stack([p, q, v], -1),
             np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
        if scaled:
            out = out * 255.0
        out = out if hwc else np.moveaxis(out, -1, -3)
        return _wrap_like(img, out.astype(np.float32))


class ColorJitter(BaseTransform):
    """Reference: transforms/transforms.py ColorJitter — randomized order of
    brightness/contrast/saturation/hue adjustments."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.tfms = [BrightnessTransform(brightness),
                     ContrastTransform(contrast),
                     SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.tfms))
        for k in order:
            img = self.tfms[k]._apply_image(img)
        return img


class RandomRotation(BaseTransform):
    """Random rotation via inverse-mapped sampling (reference:
    transforms/transforms.py RandomRotation). Supports expand (output canvas
    grows to hold the whole rotated image), a custom rotation center, and
    nearest/bilinear interpolation."""

    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_np(img).astype(np.float32)
        hwc = _is_hwc(arr)
        x = arr if hwc else np.moveaxis(arr, -3, -1)
        h, w = x.shape[:2]
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        if self.center is not None:
            cx, cy = self.center
        else:
            cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        if self.expand:
            oh = int(np.ceil(abs(h * np.cos(angle)) + abs(w * np.sin(angle))))
            ow = int(np.ceil(abs(h * np.sin(angle)) + abs(w * np.cos(angle))))
        else:
            oh, ow = h, w
        # output-pixel centers, shifted so the rotation center stays centered
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
        dy = yy - (ocy if self.expand else cy)
        dx = xx - (ocx if self.expand else cx)
        ys = dy * np.cos(angle) - dx * np.sin(angle) + cy
        xs = dy * np.sin(angle) + dx * np.cos(angle) + cx

        if self.interpolation == "nearest":
            yi = np.round(ys).astype(int)
            xi = np.round(xs).astype(int)
            valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            out = x[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
            out = np.where(valid[..., None], out, self.fill)
        else:
            y0 = np.floor(ys).astype(int)
            x0 = np.floor(xs).astype(int)
            wy = (ys - y0)[..., None]
            wx = (xs - x0)[..., None]

            def take(yi, xi):
                valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                v = x[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
                return np.where(valid[..., None], v, self.fill)

            out = (take(y0, x0) * (1 - wy) * (1 - wx)
                   + take(y0, x0 + 1) * (1 - wy) * wx
                   + take(y0 + 1, x0) * wy * (1 - wx)
                   + take(y0 + 1, x0 + 1) * wy * wx)
        out = out if hwc else np.moveaxis(out, -1, -3)
        return _wrap_like(img, out.astype(np.float32))


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, keys=None):
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = _to_np(img).astype(np.float32).copy()
        hwc = _is_hwc(arr)
        h, w = (arr.shape[0], arr.shape[1]) if hwc else arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh, ew = int(round(np.sqrt(target * ar))), \
                int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                value = self.value
                if not isinstance(value, numbers.Number):
                    value = np.asarray(value, np.float32)
                    # per-channel fill broadcasts along the channel axis
                    value = value[None, None, :] if hwc \
                        else value[:, None, None]
                if hwc:
                    arr[i:i + eh, j:j + ew] = value
                else:
                    arr[..., i:i + eh, j:j + ew] = value
                break
        return _wrap_like(img, arr)


def hflip(img):
    arr = _to_np(img)
    out = arr[:, ::-1].copy() if _is_hwc(arr) else arr[..., ::-1].copy()
    return _wrap_like(img, out)


def vflip(img):
    arr = _to_np(img)
    out = arr[::-1].copy() if _is_hwc(arr) else arr[..., ::-1, :].copy()
    return _wrap_like(img, out)


def crop(img, top, left, height, width):
    arr = _to_np(img)
    out = arr[top:top + height, left:left + width] if _is_hwc(arr) \
        else arr[..., top:top + height, left:left + width]
    return _wrap_like(img, out)


def center_crop(img, output_size):
    return CenterCrop(output_size)._apply_image(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)._apply_image(img)


def adjust_brightness(img, brightness_factor):
    arr = _to_np(img).astype(np.float32)
    return _wrap_like(img, arr * brightness_factor)


def adjust_contrast(img, contrast_factor):
    arr = _to_np(img).astype(np.float32)
    mean = arr.mean()
    return _wrap_like(img, (arr - mean) * contrast_factor + mean)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    t = RandomRotation((angle, angle), interpolation=interpolation,
                       expand=expand, center=center, fill=fill)
    return t._apply_image(img)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)._apply_image(img)


def _inverse_sample(x, ys, xs, interpolation, fill):
    """Sample HWC image x at source coords (ys, xs); out-of-bounds -> fill."""
    h, w = x.shape[:2]
    if interpolation == "nearest":
        yi = np.round(ys).astype(int)
        xi = np.round(xs).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = np.full(ys.shape + (x.shape[2],), float(fill), np.float32)
        out[valid] = x[yi[valid], xi[valid]]
        return out
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    wy = (ys - y0)[..., None]
    wx = (xs - x0)[..., None]

    def take(yi, xi):
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = np.full(yi.shape + (x.shape[2],), float(fill), np.float32)
        out[valid] = x[yi[valid], xi[valid]]
        return out
    top = take(y0, x0) * (1 - wx) + take(y0, x0 + 1) * wx
    bot = take(y0 + 1, x0) * (1 - wx) + take(y0 + 1, x0 + 1) * wx
    return top * (1 - wy) + bot * wy


def adjust_hue(img, hue_factor):
    """Functional hue shift (reference: transforms/functional.py adjust_hue).
    hue_factor in [-0.5, 0.5]."""
    t = HueTransform(abs(hue_factor) if hue_factor else 0.0)
    if hue_factor == 0:
        return img
    # reuse the HSV round-trip with a fixed shift
    arr = _to_np(img).astype(np.float32)
    hwc = _is_hwc(arr)
    x = arr if hwc else np.moveaxis(arr, -3, -1)
    scaled = x.max() > 1.5
    xf = x / 255.0 if scaled else x
    mx, mn = xf.max(-1), xf.min(-1)
    diff = mx - mn + 1e-10
    r, g, b = xf[..., 0], xf[..., 1], xf[..., 2]
    hch = np.where(mx == r, (g - b) / diff % 6,
                   np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    hch = (hch / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-10), 0)
    v = mx
    i = np.floor(hch * 6.0)
    f = hch * 6.0 - i
    p, q, tt = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    i = (i.astype(int) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, tt, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, tt], -1), np.stack([p, q, v], -1),
         np.stack([tt, p, v], -1), np.stack([v, p, q], -1)])
    if scaled:
        out = out * 255.0
    out = out if hwc else np.moveaxis(out, -1, -3)
    return _wrap_like(img, out.astype(np.float32))


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region with value v (reference: transforms/functional.py
    erase)."""
    arr = _to_np(img).astype(np.float32)
    hwc = _is_hwc(arr)
    x = arr if hwc else np.moveaxis(arr, -3, -1)
    if not inplace:
        x = x.copy()
    x[i:i + h, j:j + w] = np.asarray(v, np.float32).reshape(
        (1, 1, -1)) if np.ndim(v) else float(np.asarray(v))
    out = x if hwc else np.moveaxis(x, -1, -3)
    return _wrap_like(img, out)


def _affine_inverse_coords(h, w, angle, translate, scale, shear, center):
    """Inverse affine map: output pixel -> source pixel (torch/paddle
    parameterization: rotate+shear+scale about center, then translate)."""
    cy, cx = center
    a = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward 2x2: rotation composed with x/y shear, scaled
    R = np.array([[np.cos(a), -np.sin(a)], [np.sin(a), np.cos(a)]])
    Sh = np.array([[1, -np.tan(sx)], [0, 1]]) @ np.array(
        [[1, 0], [-np.tan(sy), 1]])
    M = scale * (R @ Sh)
    Minv = np.linalg.inv(M)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    # output coords relative to center+translate
    dx = xx - cx - translate[0]
    dy = yy - cy - translate[1]
    xs = Minv[0, 0] * dx + Minv[0, 1] * dy + cx
    ys = Minv[1, 0] * dx + Minv[1, 1] * dy + cy
    return ys, xs


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine warp (reference: transforms/functional.py affine)."""
    arr = _to_np(img).astype(np.float32)
    hwc = _is_hwc(arr)
    x = arr if hwc else np.moveaxis(arr, -3, -1)
    h, w = x.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    if center is None:
        center = ((h - 1) / 2.0, (w - 1) / 2.0)
    else:
        center = (center[1], center[0])
    ys, xs = _affine_inverse_coords(h, w, angle, translate, scale, shear,
                                    center)
    out = _inverse_sample(x, ys, xs, interpolation, fill)
    out = out if hwc else np.moveaxis(out, -1, -3)
    return _wrap_like(img, out)


def _perspective_coeffs(startpoints, endpoints):
    """Homography mapping endpoints -> startpoints (inverse warp)."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.extend([sx, sy])
    return np.linalg.solve(np.asarray(a, np.float64),
                           np.asarray(b, np.float64))


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Perspective warp by 4 point pairs (reference: transforms/functional.py
    perspective)."""
    arr = _to_np(img).astype(np.float32)
    hwc = _is_hwc(arr)
    x = arr if hwc else np.moveaxis(arr, -3, -1)
    h, w = x.shape[:2]
    c = _perspective_coeffs(startpoints, endpoints)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    denom = c[6] * xx + c[7] * yy + 1.0
    xs = (c[0] * xx + c[1] * yy + c[2]) / denom
    ys = (c[3] * xx + c[4] * yy + c[5]) / denom
    out = _inverse_sample(x, ys, xs, interpolation, fill)
    out = out if hwc else np.moveaxis(out, -1, -3)
    return _wrap_like(img, out)


class Transpose(BaseTransform):
    """HWC -> CHW (reference: transforms/transforms.py Transpose)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return _wrap_like(img, np.transpose(arr, self.order))


class RandomAffine(BaseTransform):
    """reference: transforms/transforms.py RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _to_np(img)
        h, w = (arr.shape[:2] if _is_hwc(arr) else arr.shape[-2:])
        angle = np.random.uniform(*self.degrees)
        translate = (0.0, 0.0)
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
            translate = (tx, ty)
        scale = np.random.uniform(*self.scale) if self.scale else 1.0
        shear = (0.0, 0.0)
        if self.shear is not None:
            sh = self.shear
            if isinstance(sh, numbers.Number):
                sh = (-sh, sh)
            if len(sh) == 2:
                shear = (np.random.uniform(sh[0], sh[1]), 0.0)
            else:
                shear = (np.random.uniform(sh[0], sh[1]),
                         np.random.uniform(sh[2], sh[3]))
        return affine(img, angle, translate, scale, shear,
                      self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    """reference: transforms/transforms.py RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = _to_np(img)
        h, w = (arr.shape[:2] if _is_hwc(arr) else arr.shape[-2:])
        d = self.distortion_scale
        half_h, half_w = h // 2, w // 2
        tl = (np.random.randint(0, int(d * half_w) + 1),
              np.random.randint(0, int(d * half_h) + 1))
        tr = (w - 1 - np.random.randint(0, int(d * half_w) + 1),
              np.random.randint(0, int(d * half_h) + 1))
        br = (w - 1 - np.random.randint(0, int(d * half_w) + 1),
              h - 1 - np.random.randint(0, int(d * half_h) + 1))
        bl = (np.random.randint(0, int(d * half_w) + 1),
              h - 1 - np.random.randint(0, int(d * half_h) + 1))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [tl, tr, br, bl]
        return perspective(img, start, end, self.interpolation, self.fill)
