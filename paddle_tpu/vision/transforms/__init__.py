"""Minimal vision transforms (reference: python/paddle/vision/transforms/).

Numpy-based host-side preprocessing for DataLoader pipelines.
"""
from __future__ import annotations

import numbers

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(x)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
        hwc = arr.ndim == 3 and arr.shape[-1] in (1, 3, 4)
        if hwc:
            new_shape = (*self.size, arr.shape[-1])
        else:
            new_shape = (arr.shape[0], *self.size)
        out = np.asarray(jax.image.resize(jnp.asarray(arr), new_shape, "bilinear"))
        return Tensor(out) if isinstance(img, Tensor) else out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        h, w = arr.shape[-3:-1] if arr.shape[-1] in (1, 3, 4) else arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
            out = arr[i:i + th, j:j + tw, :]
        else:
            out = arr[..., i:i + th, j:j + tw]
        return Tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
                out = arr[:, ::-1].copy()
            else:
                out = arr[..., ::-1].copy()
            return Tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = not (arr.ndim == 3 and arr.shape[-1] in (1, 3, 4))
        h, w = arr.shape[-2:] if chw else arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        out = arr[..., i:i + th, j:j + tw] if chw else arr[i:i + th, j:j + tw, :]
        return Tensor(out) if isinstance(img, Tensor) else out


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
