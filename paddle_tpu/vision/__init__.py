from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401


_IMAGE_BACKEND = "pil"


def set_image_backend(backend):
    """reference: vision/image.py set_image_backend — 'pil' or 'cv2'."""
    global _IMAGE_BACKEND
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], but got "
            f"{backend}")
    _IMAGE_BACKEND = backend


def get_image_backend():
    return _IMAGE_BACKEND


def image_load(path, backend=None):
    """reference: vision/image.py image_load."""
    backend = backend or _IMAGE_BACKEND
    if backend == "cv2":
        try:
            import cv2
            return cv2.imread(path)
        except ImportError as e:
            raise RuntimeError("cv2 backend requires opencv-python") from e
    from PIL import Image
    img = Image.open(path)
    if backend == "tensor":
        import numpy as np
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        return Tensor(jnp.asarray(np.asarray(img)))
    return img
