"""Synthetic/stub datasets (the reference downloads MNIST/Cifar; zero-egress here).

FakeImageDataset stands in for ImageNet-style loaders in benchmarks and tests.
"""
from __future__ import annotations

import numpy as np

from ...io import Dataset


class FakeImageDataset(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=1024, image_shape=(3, 224, 224), num_classes=1000,
                 seed=0, dtype="float32"):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.seed = seed
        self.dtype = dtype

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = np.int64(rng.integers(0, self.num_classes))
        return img, label

    def __len__(self):
        return self.num_samples


class FakeTextDataset(Dataset):
    """Deterministic synthetic LM token data (input_ids, labels)."""

    def __init__(self, num_samples=1024, seq_len=512, vocab_size=32000, seed=0):
        self.num_samples, self.seq_len = num_samples, seq_len
        self.vocab_size, self.seed = vocab_size, seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        ids = rng.integers(0, self.vocab_size, self.seq_len + 1, dtype=np.int64)
        return ids[:-1], ids[1:]

    def __len__(self):
        return self.num_samples




def _require(path, name, hint):
    import os
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{name} needs its data on disk (downloads are disabled in this "
            f"environment); pass {hint}")
    return path


class MNIST(Dataset):
    """MNIST from local idx files (reference: vision/datasets/mnist.py, minus
    the downloader). Pass image_path/label_path to the raw (optionally .gz)
    idx files."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        import gzip
        import struct
        _require(image_path, self.NAME, "image_path=")
        _require(label_path, self.NAME, "label_path=")
        opener = gzip.open if str(image_path).endswith(".gz") else open
        with opener(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, "not an idx3 image file"
            self.images = np.frombuffer(f.read(n * rows * cols),
                                        dtype=np.uint8).reshape(n, rows, cols)
        opener = gzip.open if str(label_path).endswith(".gz") else open
        with opener(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, "not an idx1 label file"
            self.labels = np.frombuffer(f.read(n), dtype=np.uint8)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[..., None]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class Cifar10(Dataset):
    """CIFAR-10 from a local python-version tar.gz (reference:
    vision/datasets/cifar.py minus download)."""

    _n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        import pickle
        import tarfile
        _require(data_file, type(self).__name__, "data_file=")
        imgs, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = [m.name for m in tf.getmembers()]
            for name in sorted(names):
                base = name.rsplit("/", 1)[-1]
                if self._n_classes == 10 and not base.startswith(
                        "data_batch" if mode == "train" else "test_batch"):
                    continue
                if self._n_classes == 100 and base != mode:
                    continue
                entry = pickle.loads(tf.extractfile(name).read(),
                                     encoding="bytes")
                imgs.append(np.asarray(entry[b"data"]))
                key = b"labels" if b"labels" in entry else b"fine_labels"
                labels.extend(entry[key])
        data = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        self.images = data.transpose(0, 2, 3, 1)  # HWC like the reference
        self.labels = np.asarray(labels, dtype=np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _n_classes = 100


class Flowers(Dataset):
    """Flowers-102 from local files (gated)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None):
        _require(data_file, "Flowers", "data_file=")
        raise NotImplementedError(
            "Flowers parsing requires scipy.io + image decoding; provide "
            "pre-extracted arrays or use FakeImageDataset")


class DatasetFolder(Dataset):
    """Generic folder-of-class-subfolders dataset (reference:
    vision/datasets/folder.py). Requires an image loader; numpy .npy files
    load natively, other formats need a user-provided loader."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        _require(root, "DatasetFolder", "root=")
        extensions = extensions or (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                if is_valid_file is not None:
                    if not is_valid_file(path):
                        continue
                elif not fname.lower().endswith(tuple(extensions)):
                    continue
                self.samples.append((path, self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))
        self.transform = transform

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)

    def __len__(self):
        return len(self.samples)


def _default_image_loader(path):
    if path.lower().endswith(".npy"):
        return np.load(path)
    from PIL import Image
    return Image.open(path).convert("RGB")


class ImageFolder(Dataset):
    """Flat/recursive folder of images, no labels (reference:
    vision/datasets/folder.py ImageFolder — yields [img], unlike
    DatasetFolder's (img, label))."""

    IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        _require(root, "ImageFolder", "root=")
        extensions = extensions or self.IMG_EXTENSIONS
        self.samples = []
        for base, _dirs, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(base, fname)
                if is_valid_file is not None:
                    if not is_valid_file(path):
                        continue
                elif not fname.lower().endswith(tuple(extensions)):
                    continue
                self.samples.append(path)
        self.loader = loader or _default_image_loader
        self.transform = transform

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference: vision/datasets/voc2012.py)
    over a local extracted VOCdevkit directory: yields (image, label-mask)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend=None):
        import os
        _require(data_file, "VOC2012", "data_file= (extracted VOCdevkit root)")
        root = data_file
        seg_dir = os.path.join(root, "VOC2012", "ImageSets", "Segmentation")
        list_file = {"train": "train.txt", "valid": "val.txt",
                     "test": "val.txt"}[mode]
        with open(os.path.join(seg_dir, list_file)) as f:
            names = [ln.strip() for ln in f if ln.strip()]
        self.images = [os.path.join(root, "VOC2012", "JPEGImages",
                                    n + ".jpg") for n in names]
        self.labels = [os.path.join(root, "VOC2012", "SegmentationClass",
                                    n + ".png") for n in names]
        self.transform = transform

    def __getitem__(self, idx):
        from PIL import Image
        img = np.asarray(Image.open(self.images[idx]).convert("RGB"))
        lbl = np.asarray(Image.open(self.labels[idx]))
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.images)
