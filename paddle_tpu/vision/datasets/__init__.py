"""Synthetic/stub datasets (the reference downloads MNIST/Cifar; zero-egress here).

FakeImageDataset stands in for ImageNet-style loaders in benchmarks and tests.
"""
from __future__ import annotations

import numpy as np

from ...io import Dataset


class FakeImageDataset(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=1024, image_shape=(3, 224, 224), num_classes=1000,
                 seed=0, dtype="float32"):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.seed = seed
        self.dtype = dtype

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = np.int64(rng.integers(0, self.num_classes))
        return img, label

    def __len__(self):
        return self.num_samples


class FakeTextDataset(Dataset):
    """Deterministic synthetic LM token data (input_ids, labels)."""

    def __init__(self, num_samples=1024, seq_len=512, vocab_size=32000, seed=0):
        self.num_samples, self.seq_len = num_samples, seq_len
        self.vocab_size, self.seed = vocab_size, seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        ids = rng.integers(0, self.vocab_size, self.seq_len + 1, dtype=np.int64)
        return ids[:-1], ids[1:]

    def __len__(self):
        return self.num_samples


MNIST = None  # requires download; out of scope in a zero-egress environment
Cifar10 = None
