"""DenseNet / GoogLeNet / InceptionV3 / ShuffleNetV2 (reference:
python/paddle/vision/models/{densenet.py,googlenet.py,inceptionv3.py,
shufflenetv2.py})."""
from __future__ import annotations

from ...nn import (
    Layer, Conv2D, BatchNorm2D, ReLU, MaxPool2D, AvgPool2D,
    AdaptiveAvgPool2D, Linear, Sequential, Dropout,
)
from ... import ops

__all__ = [
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264", "GoogLeNet", "googlenet", "InceptionV3", "inception_v3",
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
    "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
]


# ---------------------------------------------------------------------------
# DenseNet
# ---------------------------------------------------------------------------

class _DenseLayer(Layer):
    def __init__(self, in_ch, growth_rate, bn_size):
        super().__init__()
        self.bn1 = BatchNorm2D(in_ch)
        self.conv1 = Conv2D(in_ch, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                            bias_attr=False)
        self.relu = ReLU()

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        return ops.concat([x, out], axis=1)


class _Transition(Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = BatchNorm2D(in_ch)
        self.conv = Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.pool = AvgPool2D(2, 2)
        self.relu = ReLU()

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_DENSE_CFG = {
    121: (6, 12, 24, 16), 161: (6, 12, 36, 24), 169: (6, 12, 32, 32),
    201: (6, 12, 48, 32), 264: (6, 12, 64, 48),
}


class DenseNet(Layer):
    """Reference: vision/models/densenet.py."""

    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers == 161:
            growth_rate, init_ch = 48, 96
        else:
            init_ch = 64
        block_cfg = _DENSE_CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [Sequential(
            Conv2D(3, init_ch, 7, stride=2, padding=3, bias_attr=False),
            BatchNorm2D(init_ch), ReLU(), MaxPool2D(3, 2, 1))]
        ch = init_ch
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth_rate, bn_size))
                ch += growth_rate
            if i != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats.append(BatchNorm2D(ch))
        feats.append(ReLU())
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(264, **kw)


# ---------------------------------------------------------------------------
# GoogLeNet (Inception v1)
# ---------------------------------------------------------------------------

def _bn_conv(in_ch, out_ch, k, stride=1, padding=0):
    return Sequential(
        Conv2D(in_ch, out_ch, k, stride=stride, padding=padding,
               bias_attr=False),
        BatchNorm2D(out_ch), ReLU())


class _Inception(Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        self.b1 = _bn_conv(in_ch, c1, 1)
        self.b2 = Sequential(_bn_conv(in_ch, c3r, 1), _bn_conv(c3r, c3, 3,
                                                               padding=1))
        self.b3 = Sequential(_bn_conv(in_ch, c5r, 1), _bn_conv(c5r, c5, 5,
                                                               padding=2))
        self.b4 = Sequential(MaxPool2D(3, 1, 1), _bn_conv(in_ch, pool_proj, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                          axis=1)


class GoogLeNet(Layer):
    """Reference: vision/models/googlenet.py (returns main + 2 aux logits in
    train, like the reference)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _bn_conv(3, 64, 7, stride=2, padding=3), MaxPool2D(3, 2, 1),
            _bn_conv(64, 64, 1), _bn_conv(64, 192, 3, padding=1),
            MaxPool2D(3, 2, 1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, 2, 1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, 2, 1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x)))))
        x = self.pool4(x)
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(ops.flatten(x, 1)))
        return x


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


# ---------------------------------------------------------------------------
# InceptionV3 (compact faithful structure)
# ---------------------------------------------------------------------------

class _IncA(Layer):
    def __init__(self, in_ch, pool_feat):
        super().__init__()
        self.b1 = _bn_conv(in_ch, 64, 1)
        self.b5 = Sequential(_bn_conv(in_ch, 48, 1),
                             _bn_conv(48, 64, 5, padding=2))
        self.b3 = Sequential(_bn_conv(in_ch, 64, 1),
                             _bn_conv(64, 96, 3, padding=1),
                             _bn_conv(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, 1, 1), _bn_conv(in_ch, pool_feat, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                          axis=1)


class _IncB(Layer):  # grid reduction
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _bn_conv(in_ch, 384, 3, stride=2)
        self.b3d = Sequential(_bn_conv(in_ch, 64, 1),
                              _bn_conv(64, 96, 3, padding=1),
                              _bn_conv(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncC(Layer):
    def __init__(self, in_ch, c7):
        super().__init__()
        self.b1 = _bn_conv(in_ch, 192, 1)
        self.b7 = Sequential(
            _bn_conv(in_ch, c7, 1), _bn_conv(c7, c7, (1, 7), padding=(0, 3)),
            _bn_conv(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            _bn_conv(in_ch, c7, 1), _bn_conv(c7, c7, (7, 1), padding=(3, 0)),
            _bn_conv(c7, c7, (1, 7), padding=(0, 3)),
            _bn_conv(c7, c7, (7, 1), padding=(3, 0)),
            _bn_conv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, 1, 1), _bn_conv(in_ch, 192, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                          axis=1)


class _IncD(Layer):  # grid reduction
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = Sequential(_bn_conv(in_ch, 192, 1),
                             _bn_conv(192, 320, 3, stride=2))
        self.b7 = Sequential(
            _bn_conv(in_ch, 192, 1),
            _bn_conv(192, 192, (1, 7), padding=(0, 3)),
            _bn_conv(192, 192, (7, 1), padding=(3, 0)),
            _bn_conv(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncE(Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _bn_conv(in_ch, 320, 1)
        self.b3_stem = _bn_conv(in_ch, 384, 1)
        self.b3_a = _bn_conv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _bn_conv(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = Sequential(_bn_conv(in_ch, 448, 1),
                                   _bn_conv(448, 384, 3, padding=1))
        self.b3d_a = _bn_conv(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _bn_conv(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, 1, 1), _bn_conv(in_ch, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return ops.concat(
            [self.b1(x), self.b3_a(s), self.b3_b(s), self.b3d_a(d),
             self.b3d_b(d), self.bp(x)], axis=1)


class InceptionV3(Layer):
    """Reference: vision/models/inceptionv3.py (299x299 input)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _bn_conv(3, 32, 3, stride=2), _bn_conv(32, 32, 3),
            _bn_conv(32, 64, 3, padding=1), MaxPool2D(3, 2),
            _bn_conv(64, 80, 1), _bn_conv(80, 192, 3), MaxPool2D(3, 2))
        self.blocks = Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160), _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048))
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(ops.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)


# ---------------------------------------------------------------------------
# ShuffleNetV2
# ---------------------------------------------------------------------------

def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = ops.reshape(x, [n, groups, c // groups, h, w])
    x = ops.transpose(x, [0, 2, 1, 3, 4])
    return ops.reshape(x, [n, c, h, w])


class _ShuffleUnit(Layer):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 1:
            self.b2 = Sequential(
                _bn_conv(branch, branch, 1),
                Sequential(Conv2D(branch, branch, 3, stride=1, padding=1,
                                  groups=branch, bias_attr=False),
                           BatchNorm2D(branch)),
                _bn_conv(branch, branch, 1))
        else:
            self.b1 = Sequential(
                Sequential(Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                                  groups=in_ch, bias_attr=False),
                           BatchNorm2D(in_ch)),
                _bn_conv(in_ch, branch, 1))
            self.b2 = Sequential(
                _bn_conv(in_ch, branch, 1),
                Sequential(Conv2D(branch, branch, 3, stride=stride, padding=1,
                                  groups=branch, bias_attr=False),
                           BatchNorm2D(branch)),
                _bn_conv(branch, branch, 1))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1 = x[:, :half]
            x2 = x[:, half:]
            out = ops.concat([x1, self.b2(x2)], axis=1)
        else:
            out = ops.concat([self.b1(x), self.b2(x)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    0.25: (24, (24, 48, 96), 512), 0.33: (24, (32, 64, 128), 512),
    0.5: (24, (48, 96, 192), 1024),
    1.0: (24, (116, 232, 464), 1024), 1.5: (24, (176, 352, 704), 1024),
    2.0: (24, (244, 488, 976), 2048),
}


class ShuffleNetV2(Layer):
    """Reference: vision/models/shufflenetv2.py."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        stem_ch, stage_chs, last_ch = _SHUFFLE_CFG[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(_bn_conv(3, stem_ch, 3, stride=2, padding=1),
                               MaxPool2D(3, 2, 1))
        stages = []
        in_ch = stem_ch
        for out_ch, repeat in zip(stage_chs, (4, 8, 4)):
            units = [_ShuffleUnit(in_ch, out_ch, 2)]
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(out_ch, out_ch, 1))
            stages.append(Sequential(*units))
            in_ch = out_ch
        self.stages = Sequential(*stages)
        self.last_conv = _bn_conv(in_ch, last_ch, 1)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(last_ch, num_classes)

    def forward(self, x):
        x = self.last_conv(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    """reference: vision/models/shufflenetv2.py shufflenet_v2_swish — the
    1.0x net with swish activations."""
    return ShuffleNetV2(1.0, act="swish", **kw)
