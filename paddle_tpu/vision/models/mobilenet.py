"""MobileNet v1/v2/v3 (reference: python/paddle/vision/models/
{mobilenetv1.py,mobilenetv2.py,mobilenetv3.py}).

Depthwise convs map to XLA's feature_group_count path — on TPU they lower to
the dedicated depthwise conv HLO rather than grouped MXU matmuls.
"""
from __future__ import annotations

from ...nn import (
    Layer, Conv2D, BatchNorm2D, ReLU, ReLU6, Hardswish, Hardsigmoid,
    AdaptiveAvgPool2D, Linear, Sequential, Dropout,
)
from ... import ops

__all__ = ["MobileNetV1", "mobilenet_v1", "MobileNetV2", "mobilenet_v2",
           "MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(in_ch, out_ch, k, stride=1, groups=1, act=ReLU):
    pad = (k - 1) // 2
    layers = [Conv2D(in_ch, out_ch, k, stride=stride, padding=pad,
                     groups=groups, bias_attr=False), BatchNorm2D(out_ch)]
    if act is not None:
        layers.append(act())
    return Sequential(*layers)


class MobileNetV1(Layer):
    """Reference: mobilenetv1.py (depthwise-separable stacks)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # (out, stride)
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1),
        ]
        ch = int(32 * scale)
        layers = [_conv_bn(3, ch, 3, stride=2)]
        for out, s in cfg:
            out = int(out * scale)
            layers.append(_conv_bn(ch, ch, 3, stride=s, groups=ch))  # dw
            layers.append(_conv_bn(ch, out, 1))                      # pw
            ch = out
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


class _InvertedResidual(Layer):
    def __init__(self, in_ch, out_ch, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_ch * expand_ratio))
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(in_ch, hidden, 1, act=ReLU6))
        layers += [
            _conv_bn(hidden, hidden, 3, stride=stride, groups=hidden,
                     act=ReLU6),
            _conv_bn(hidden, out_ch, 1, act=None),
        ]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    """Reference: mobilenetv2.py."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_ch = _make_divisible(32 * scale)
        layers = [_conv_bn(3, in_ch, 3, stride=2, act=ReLU6)]
        for t, c, n, s in cfg:
            out_ch = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(
                    in_ch, out_ch, s if i == 0 else 1, t))
                in_ch = out_ch
        self.last_ch = _make_divisible(1280 * max(1.0, scale))
        layers.append(_conv_bn(in_ch, self.last_ch, 1, act=ReLU6))
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(self.last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


class _SEModule(Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        squeeze = _make_divisible(ch // reduction)
        self.pool = AdaptiveAvgPool2D((1, 1))
        self.fc = Sequential(
            Conv2D(ch, squeeze, 1), ReLU(),
            Conv2D(squeeze, ch, 1), Hardsigmoid())

    def forward(self, x):
        return x * self.fc(self.pool(x))


class _V3Block(Layer):
    def __init__(self, in_ch, exp, out_ch, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if exp != in_ch:
            layers.append(_conv_bn(in_ch, exp, 1, act=act))
        layers.append(_conv_bn(exp, exp, k, stride=stride, groups=exp,
                               act=act))
        if use_se:
            layers.append(_SEModule(exp))
        layers.append(_conv_bn(exp, out_ch, 1, act=None))
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


_V3_SMALL = [  # k, exp, out, se, act, s
    (3, 16, 16, True, ReLU, 2), (3, 72, 24, False, ReLU, 2),
    (3, 88, 24, False, ReLU, 1), (5, 96, 40, True, Hardswish, 2),
    (5, 240, 40, True, Hardswish, 1), (5, 240, 40, True, Hardswish, 1),
    (5, 120, 48, True, Hardswish, 1), (5, 144, 48, True, Hardswish, 1),
    (5, 288, 96, True, Hardswish, 2), (5, 576, 96, True, Hardswish, 1),
    (5, 576, 96, True, Hardswish, 1),
]

_V3_LARGE = [
    (3, 16, 16, False, ReLU, 1), (3, 64, 24, False, ReLU, 2),
    (3, 72, 24, False, ReLU, 1), (5, 72, 40, True, ReLU, 2),
    (5, 120, 40, True, ReLU, 1), (5, 120, 40, True, ReLU, 1),
    (3, 240, 80, False, Hardswish, 2), (3, 200, 80, False, Hardswish, 1),
    (3, 184, 80, False, Hardswish, 1), (3, 184, 80, False, Hardswish, 1),
    (3, 480, 112, True, Hardswish, 1), (3, 672, 112, True, Hardswish, 1),
    (5, 672, 160, True, Hardswish, 2), (5, 960, 160, True, Hardswish, 1),
    (5, 960, 160, True, Hardswish, 1),
]


class _MobileNetV3(Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_ch = _make_divisible(16 * scale)
        layers = [_conv_bn(3, in_ch, 3, stride=2, act=Hardswish)]
        for k, exp, out, se, act, s in cfg:
            exp_ch = _make_divisible(exp * scale)
            out_ch = _make_divisible(out * scale)
            layers.append(_V3Block(in_ch, exp_ch, out_ch, k, s, se, act))
            in_ch = out_ch
        last_conv = _make_divisible(last_exp * scale)
        layers.append(_conv_bn(in_ch, last_conv, 1, act=Hardswish))
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            head = _make_divisible(1280 * scale) if cfg is _V3_LARGE \
                else _make_divisible(1024 * scale)
            self.classifier = Sequential(
                Linear(last_conv, head), Hardswish(), Dropout(0.2),
                Linear(head, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
