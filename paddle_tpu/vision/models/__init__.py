from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, resnext50_32x4d,
    wide_resnet50_2, BasicBlock, BottleneckBlock,
)
from .vit import VisionTransformer, vit_base_patch16, vit_large_patch16  # noqa: F401
from .small_nets import (  # noqa: F401
    LeNet, AlexNet, alexnet, SqueezeNet, squeezenet1_0, squeezenet1_1,
    VGG, vgg11, vgg13, vgg16, vgg19,
)
from .mobilenet import (  # noqa: F401
    MobileNetV1, mobilenet_v1, MobileNetV2, mobilenet_v2,
    MobileNetV3Small, MobileNetV3Large, mobilenet_v3_small, mobilenet_v3_large,
)
from .densenet_inception import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201, densenet264,
    GoogLeNet, googlenet, InceptionV3, inception_v3,
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_5, shufflenet_v2_x1_0,
    shufflenet_v2_x1_5, shufflenet_v2_x2_0, shufflenet_v2_x0_33,
    shufflenet_v2_swish,
)
from .resnet import _resnet as _resnet_factory  # noqa: F401


def resnext101_32x4d(pretrained=False, **kwargs):
    return _resnet_factory(BottleneckBlock, 101, groups=32, width=4, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _resnet_factory(BottleneckBlock, 152, groups=32, width=4, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return _resnet_factory(BottleneckBlock, 101, width=128, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _resnet_factory(BottleneckBlock, 50, groups=64, width=4, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnet_factory(BottleneckBlock, 101, groups=64, width=4, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _resnet_factory(BottleneckBlock, 152, groups=64, width=4, **kwargs)
