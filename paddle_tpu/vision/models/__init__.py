from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, resnext50_32x4d,
    wide_resnet50_2, BasicBlock, BottleneckBlock,
)
from .vit import VisionTransformer, vit_base_patch16, vit_large_patch16  # noqa: F401
