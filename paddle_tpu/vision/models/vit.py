"""Vision Transformer (BASELINE config 4: ViT-L semi-auto sharding).

Reference analog: ViT lives in PaddleClas on top of paddle.nn; here it is in-tree
since it is a named baseline workload.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...nn import (
    Layer, Linear, LayerNorm, Dropout, Conv2D, LayerList, GELU, Sequential,
)
from ...nn.layer_base import Parameter
from ...nn import functional as F
from ...core.tensor import Tensor
from ... import ops


class PatchEmbed(Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = Conv2D(in_chans, embed_dim, patch_size, stride=patch_size)

    def forward(self, x):
        x = self.proj(x)                       # [B, E, H/p, W/p]
        b, e = x.shape[0], x.shape[1]
        x = ops.reshape(x, [b, e, -1])
        return ops.transpose(x, [0, 2, 1])     # [B, N, E]


class ViTAttention(Layer):
    def __init__(self, dim, num_heads, qkv_bias=True, attn_drop=0.0, proj_drop=0.0):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.qkv = Linear(dim, dim * 3, bias_attr=None if qkv_bias else False)
        self.proj = Linear(dim, dim)
        self.attn_drop = attn_drop
        self.proj_dropout = Dropout(proj_drop)

    def forward(self, x):
        b, n, c = x.shape
        qkv = ops.reshape(self.qkv(x), [b, n, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unbind(ops.transpose(qkv, [2, 0, 1, 3, 4]), axis=0)
        out = F.scaled_dot_product_attention(q, k, v, dropout_p=self.attn_drop,
                                             training=self.training)
        out = ops.reshape(out, [b, n, c])
        return self.proj_dropout(self.proj(out))


class ViTBlock(Layer):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, qkv_bias=True, drop=0.0,
                 attn_drop=0.0):
        super().__init__()
        self.norm1 = LayerNorm(dim, 1e-6)
        self.attn = ViTAttention(dim, num_heads, qkv_bias, attn_drop, drop)
        self.norm2 = LayerNorm(dim, 1e-6)
        hidden = int(dim * mlp_ratio)
        self.mlp = Sequential(Linear(dim, hidden), GELU(), Dropout(drop),
                              Linear(hidden, dim), Dropout(drop))

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        x = x + self.mlp(self.norm2(x))
        return x


class VisionTransformer(Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, num_classes=1000,
                 embed_dim=768, depth=12, num_heads=12, mlp_ratio=4.0, qkv_bias=True,
                 drop_rate=0.0, attn_drop_rate=0.0, **kwargs):
        super().__init__()
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans, embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = Parameter(jnp.zeros((1, 1, embed_dim), jnp.float32))
        from ...core import random as _random
        import jax
        self.pos_embed = Parameter(
            0.02 * jax.random.normal(_random.next_key(), (1, n + 1, embed_dim),
                                     jnp.float32))
        self.pos_drop = Dropout(drop_rate)
        self.blocks = LayerList([
            ViTBlock(embed_dim, num_heads, mlp_ratio, qkv_bias, drop_rate,
                     attn_drop_rate) for _ in range(depth)])
        self.norm = LayerNorm(embed_dim, 1e-6)
        self.head = Linear(embed_dim, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.patch_embed(x)
        b = x.shape[0]
        cls = ops.expand(self.cls_token, [b, -1, -1])
        x = ops.concat([cls, x], axis=1)
        x = self.pos_drop(x + self.pos_embed)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        if self.head is not None:
            return self.head(x[:, 0])
        return x[:, 0]


def vit_base_patch16(**kwargs):
    return VisionTransformer(embed_dim=768, depth=12, num_heads=12, **kwargs)


def vit_large_patch16(**kwargs):
    return VisionTransformer(embed_dim=1024, depth=24, num_heads=16, **kwargs)
