"""LeNet / AlexNet / SqueezeNet / VGG (reference: python/paddle/vision/models/
{lenet.py,alexnet.py,squeezenet.py,vgg.py}). NCHW like the reference; XLA
retiles to TPU-preferred layouts internally."""
from __future__ import annotations

from ...nn import (
    Layer, Conv2D, BatchNorm2D, ReLU, MaxPool2D, AdaptiveAvgPool2D,
    AvgPool2D, Linear, Sequential, Dropout, Flatten,
)
from ... import ops

__all__ = ["LeNet", "AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1", "VGG", "vgg11", "vgg13", "vgg16", "vgg19"]


class LeNet(Layer):
    """Reference: vision/models/lenet.py (MNIST 1x28x28 input)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = Sequential(
                Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


class AlexNet(Layer):
    """Reference: vision/models/alexnet.py."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(), MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(), MaxPool2D(3, 2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(dropout), Linear(256 * 36, 4096), ReLU(),
                Dropout(dropout), Linear(4096, 4096), ReLU(),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Sequential(Conv2D(in_ch, squeeze, 1), ReLU())
        self.expand1 = Sequential(Conv2D(squeeze, e1, 1), ReLU())
        self.expand3 = Sequential(Conv2D(squeeze, e3, 3, padding=1), ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        return ops.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(Layer):
    """Reference: vision/models/squeezenet.py (version '1.0'/'1.1')."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5), Conv2D(512, num_classes, 1), ReLU())
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return ops.flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
          "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
          512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg, batch_norm):
    layers = []
    in_ch = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, 2))
        else:
            layers.append(Conv2D(in_ch, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            in_ch = v
    return Sequential(*layers)


class VGG(Layer):
    """Reference: vision/models/vgg.py."""

    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 49, 4096), ReLU(), Dropout(),
                Linear(4096, 4096), ReLU(), Dropout(),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


def _vgg(cfg, batch_norm=False, **kwargs):
    return VGG(_vgg_features(_VGG_CFGS[cfg], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, **kwargs)
