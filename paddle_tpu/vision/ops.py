"""paddle.vision.ops analog — detection/vision operators.

Reference: python/paddle/vision/ops.py (nms, roi_align:1130, roi_pool,
box_coder, deform_conv2d, distribute_fpn_proposals, PSRoIPool). TPU-native:
RoI ops are bilinear gathers (XLA gather HLO); NMS is a lax.fori-style
suppression over a statically-shaped score ordering (no dynamic shapes inside
jit); deform_conv2d assembles its sampling grid with vectorized gathers.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch

__all__ = ["nms", "roi_align", "roi_pool", "box_iou", "deform_conv2d",
           "PSRoIPool", "psroi_pool", "DeformConv2D", "RoIAlign", "RoIPool"]


def _box_iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU (M, N) for xyxy boxes."""
    return dispatch(_box_iou_matrix, (boxes1, boxes2), {}, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS returning kept indices sorted by score.

    Reference: vision/ops.py nms. Static-shape friendly: the suppression loop
    is a lax.fori_loop over the fixed box count, so it jit-compiles.
    """
    n = int(boxes.shape[0])

    def fn(bx, sc, cat):
        order = jnp.argsort(-sc) if sc is not None \
            else jnp.arange(n)
        b_sorted = bx[order]
        iou = _box_iou_matrix(b_sorted, b_sorted)
        if cat is not None:
            c_sorted = cat[order]
            same = c_sorted[:, None] == c_sorted[None, :]
            iou = jnp.where(same, iou, 0.0)  # cross-category never suppresses

        def body(i, keep):
            # i suppressed already? then it can't suppress others
            sup = (iou[i] > iou_threshold) & keep[i]
            sup = sup & (jnp.arange(n) > i)  # only later (lower-score) boxes
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        return order, keep

    sc_val = scores
    order_t, keep_t = dispatch(fn, (boxes, sc_val, category_idxs), {},
                               name="nms")
    order = np.asarray(order_t._value)
    keep = np.asarray(keep_t._value)
    kept = order[keep]
    if top_k is not None:
        kept = kept[:top_k]
    from ..ops.creation import to_tensor
    return to_tensor(kept.astype(np.int64))


def _bilinear_sample(feat, ys, xs):
    """feat: (C, H, W); ys/xs arbitrary same-shape float coords."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = ys - y0
    wx1 = xs - x0
    wy0, wx0 = 1 - wy1, 1 - wx1

    def at(yi, xi):
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = feat[:, yc, xc]  # (C, ...)
        return jnp.where(valid, v, 0.0)

    return (at(y0, x0) * wy0 * wx0 + at(y0, x1) * wy0 * wx1
            + at(y1, x0) * wy1 * wx0 + at(y1, x1) * wy1 * wx1)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference: vision/ops.py:1130). boxes: (R, 4) xyxy in input
    coords; boxes_num: per-image box counts."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    nums = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                      else boxes_num).astype(np.int64)
    img_ids = jnp.asarray(np.repeat(np.arange(len(nums)), nums))
    if sampling_ratio > 0:
        ratio = sampling_ratio
    else:
        # adaptive (reference: ceil(roi_size / pooled_size) per RoI). Static
        # shapes require one grid, so use the max needed ratio across the
        # (host-resident) boxes, capped to keep the gather bounded.
        try:
            bx_np = np.asarray(boxes._value if isinstance(boxes, Tensor)
                               else boxes, dtype=np.float64)
            rh = (bx_np[:, 3] - bx_np[:, 1]) * spatial_scale / output_size[0]
            rw = (bx_np[:, 2] - bx_np[:, 0]) * spatial_scale / output_size[1]
            ratio = int(min(max(np.ceil(max(rh.max(), rw.max(), 1.0)), 1), 8))
        except Exception:  # traced boxes under jit — fixed fallback
            ratio = 2

    def fn(feat, bx):
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sample grid: (R, ph, ratio) x (R, pw, ratio)
        iy = (jnp.arange(ph)[None, :, None]
              + (jnp.arange(ratio)[None, None, :] + 0.5) / ratio)
        ix = (jnp.arange(pw)[None, :, None]
              + (jnp.arange(ratio)[None, None, :] + 0.5) / ratio)
        ys = y1[:, None, None] + iy * bin_h[:, None, None]   # (R, ph, r)
        xs = x1[:, None, None] + ix * bin_w[:, None, None]   # (R, pw, r)

        def per_roi(img_id, ys_r, xs_r):
            feat_i = feat[img_id]
            yy = ys_r[:, :, None, None]                       # (ph, r, 1, 1)
            xx = xs_r[None, None, :, :]                       # (1, 1, pw, r)
            yy = jnp.broadcast_to(yy, (ph, ratio, pw, ratio))
            xx = jnp.broadcast_to(xx, (ph, ratio, pw, ratio))
            vals = _bilinear_sample(feat_i, yy, xx)           # (C, ph,r,pw,r)
            return vals.mean(axis=(2, 4))                     # (C, ph, pw)

        return jax.vmap(per_roi)(img_ids, ys, xs)

    return dispatch(fn, (x, boxes), {}, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool: max over quantized bins (reference: vision/ops.py roi_pool)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    nums = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                      else boxes_num).astype(np.int64)
    img_ids = jnp.asarray(np.repeat(np.arange(len(nums)), nums))

    def fn(feat, bx):
        H, W = feat.shape[-2], feat.shape[-1]
        x1 = jnp.round(bx[:, 0] * spatial_scale)
        y1 = jnp.round(bx[:, 1] * spatial_scale)
        x2 = jnp.round(bx[:, 2] * spatial_scale)
        y2 = jnp.round(bx[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        yy = jnp.arange(H, dtype=jnp.float32)
        xx = jnp.arange(W, dtype=jnp.float32)

        def per_roi(img_id, px1, py1, bh, bw):
            feat_i = feat[img_id]  # (C, H, W)
            # bin membership masks per output cell (static shapes)
            ys0 = py1 + jnp.arange(ph) * bh
            ys1 = py1 + (jnp.arange(ph) + 1) * bh
            xs0 = px1 + jnp.arange(pw) * bw
            xs1 = px1 + (jnp.arange(pw) + 1) * bw
            ymask = (yy[None, :] >= jnp.floor(ys0)[:, None]) \
                & (yy[None, :] < jnp.ceil(ys1)[:, None])      # (ph, H)
            xmask = (xx[None, :] >= jnp.floor(xs0)[:, None]) \
                & (xx[None, :] < jnp.ceil(xs1)[:, None])      # (pw, W)
            m = ymask[:, None, :, None] & xmask[None, :, None, :]
            big = jnp.where(m[None], feat_i[:, None, None, :, :], -jnp.inf)
            out = big.max(axis=(-2, -1))                      # (C, ph, pw)
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(per_roi)(img_ids, x1, y1, bin_h, bin_w)

    return dispatch(fn, (x, boxes), {}, name="roi_pool")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference: vision/ops.py psroi_pool):
    input channels C = out_c * ph * pw; cell (i, j) pools its own channel
    group, average-pooled."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    nums = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                      else boxes_num).astype(np.int64)
    img_ids = jnp.asarray(np.repeat(np.arange(len(nums)), nums))

    def fn(feat, bx):
        C = feat.shape[1]
        out_c = C // (ph * pw)
        x1 = bx[:, 0] * spatial_scale
        y1 = bx[:, 1] * spatial_scale
        x2 = bx[:, 2] * spatial_scale
        y2 = bx[:, 3] * spatial_scale
        bin_h = jnp.maximum(y2 - y1, 0.1) / ph
        bin_w = jnp.maximum(x2 - x1, 0.1) / pw
        ratio = 2

        def per_roi(img_id, px1, py1, bh, bw):
            feat_i = feat[img_id].reshape(out_c, ph, pw, *feat.shape[-2:])
            iy = (jnp.arange(ph)[:, None]
                  + (jnp.arange(ratio)[None, :] + 0.5) / ratio)
            ix = (jnp.arange(pw)[:, None]
                  + (jnp.arange(ratio)[None, :] + 0.5) / ratio)
            ys = py1 + iy * bh                                  # (ph, r)
            xs = px1 + ix * bw                                  # (pw, r)
            cells = []
            for i in range(ph):
                row = []
                for j in range(pw):
                    yy = jnp.broadcast_to(ys[i][:, None], (ratio, ratio))
                    xx = jnp.broadcast_to(xs[j][None, :], (ratio, ratio))
                    v = _bilinear_sample(feat_i[:, i, j], yy, xx)
                    row.append(v.mean(axis=(-2, -1)))           # (out_c,)
                cells.append(jnp.stack(row, axis=-1))           # (out_c, pw)
            return jnp.stack(cells, axis=-2)                    # (out_c,ph,pw)

        return jax.vmap(per_roi)(img_ids, x1, y1, bin_h, bin_w)

    return dispatch(fn, (x, boxes), {}, name="psroi_pool")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference: vision/ops.py deform_conv2d).

    offset: (N, 2 * dg * kh * kw, Hout, Wout); mask (v2): (N, dg*kh*kw, ...).
    Implementation: bilinear-gather the deformed sampling grid into an im2col
    tensor, then one big matmul — the MXU-friendly formulation.
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(xv, off, w, m, b):
        N, C, H, W = xv.shape
        out_ch, in_per_g, kh, kw = w.shape
        Hout = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        Wout = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        dg = deformable_groups
        off = off.reshape(N, dg, kh * kw, 2, Hout, Wout)
        base_y = jnp.arange(Hout) * stride[0] - padding[0]    # (Hout,)
        base_x = jnp.arange(Wout) * stride[1] - padding[1]    # (Wout,)
        ky_full = jnp.repeat(jnp.arange(kh) * dilation[0], kw)  # (kh*kw,)
        kx_full = jnp.tile(jnp.arange(kw) * dilation[1], kh)    # (kh*kw,)
        grid_y = base_y[None, :, None] + ky_full[:, None, None]  # (khkw,Ho,1)
        grid_x = base_x[None, None, :] + kx_full[:, None, None]  # (khkw,1,Wo)

        def per_image(xi, offi, mi):
            cols = []
            c_per_dg = C // dg
            for g in range(dg):
                ys = grid_y + offi[g, :, 0]                  # (khkw,Hout,Wout)
                xs = grid_x + offi[g, :, 1]
                feat = xi[g * c_per_dg:(g + 1) * c_per_dg]
                v = _bilinear_sample(feat, ys, xs)           # (c, khkw, Ho,Wo)
                if mi is not None:
                    v = v * mi[g][None]
                cols.append(v)
            col = jnp.concatenate(cols, axis=0)              # (C, khkw, Ho,Wo)
            return col

        if m is not None:
            mi = m.reshape(N, dg, kh * kw, Hout, Wout)
            col = jax.vmap(per_image)(xv, off, mi)
        else:
            col = jax.vmap(lambda a, o: per_image(a, o, None))(xv, off)
        # (N, C, khkw, Ho, Wo) x w(out, C/g, kh, kw)
        col = col.reshape(N, groups, C // groups, kh * kw, Hout * Wout)
        wg = w.reshape(groups, out_ch // groups, in_per_g * kh * kw)
        col2 = col.reshape(N, groups, (C // groups) * kh * kw, Hout * Wout)
        out = jnp.einsum("goi,ngiw->ngow", wg, col2)
        out = out.reshape(N, out_ch, Hout, Wout)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    return dispatch(fn, (x, offset, weight, mask, bias), {},
                    name="deform_conv2d")


# ---------------------------------------------------------------------------
# layer wrappers
# ---------------------------------------------------------------------------

from ..nn.layer_base import Layer  # noqa: E402


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._args[0],
                         spatial_scale=self._args[1])


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._args[0],
                        spatial_scale=self._args[1])


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._args[0],
                          spatial_scale=self._args[1])


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = (stride, padding, dilation, deformable_groups, groups)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + k, attr=weight_attr)
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, offset, mask=None):
        stride, padding, dilation, dg, groups = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias, stride,
                             padding, dilation, dg, groups, mask)


# ---------------------------------------------------------------------------
# Detection ops (reference: python/paddle/vision/ops.py → phi detection
# kernels). Box post-processing (prior/coder/nms/proposals) is host-side
# numpy — it is control-flow heavy and gradient-free; yolo_loss keeps its
# compute on device (dispatch) so the head gets gradients.
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference: vision/ops.py prior_box → prior_box op)."""
    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for h in range(H):
        for w in range(W):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            cell = []
            for i, ms in enumerate(min_sizes):
                if min_max_aspect_ratios_order:
                    cell.append((cx, cy, ms, ms))
                    if max_sizes:
                        bs = float(np.sqrt(ms * max_sizes[i]))
                        cell.append((cx, cy, bs, bs))
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        cell.append((cx, cy, ms * np.sqrt(ar),
                                     ms / np.sqrt(ar)))
                else:
                    for ar in ars:
                        cell.append((cx, cy, ms * np.sqrt(ar),
                                     ms / np.sqrt(ar)))
                    if max_sizes:
                        bs = float(np.sqrt(ms * max_sizes[i]))
                        cell.append((cx, cy, bs, bs))
            for cx_, cy_, bw, bh in cell:
                box = [(cx_ - bw / 2) / img_w, (cy_ - bh / 2) / img_h,
                       (cx_ + bw / 2) / img_w, (cy_ + bh / 2) / img_h]
                if clip:
                    box = [min(max(v, 0.0), 1.0) for v in box]
                boxes.append(box)
    num_priors = len(boxes) // (H * W)
    out = np.asarray(boxes, np.float32).reshape(H, W, num_priors, 4)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors (reference: vision/ops.py box_coder
    → phi box_coder kernel)."""
    pb = np.asarray(prior_box._value, np.float32)
    pbv = None if prior_box_var is None else np.asarray(
        prior_box_var._value if hasattr(prior_box_var, "_value")
        else prior_box_var, np.float32)
    tb = np.asarray(target_box._value, np.float32)
    norm = 0 if box_normalized else 1
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        # every target against every prior
        out = np.zeros((tb.shape[0], pb.shape[0], 4), np.float32)
        out[..., 0] = (tcx[:, None] - pcx[None]) / pw[None]
        out[..., 1] = (tcy[:, None] - pcy[None]) / ph[None]
        out[..., 2] = np.log(np.abs(tw[:, None] / pw[None]))
        out[..., 3] = np.log(np.abs(th[:, None] / ph[None]))
        if pbv is not None:
            out = out / (pbv.reshape(1, -1, 4) if pbv.ndim == 2
                         else pbv.reshape(1, 1, 4))
    else:  # decode_center_size
        # target_box (N, M, 4) deltas decoded against priors along `axis`
        if pbv is not None and pbv.ndim == 1:
            pbv = np.broadcast_to(pbv, pb.shape).copy()
        deltas = tb
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (v[None, :] for v in (pw, ph, pcx, pcy))
            var = pbv[None] if pbv is not None else 1.0
        else:
            pw_, ph_, pcx_, pcy_ = (v[:, None] for v in (pw, ph, pcx, pcy))
            var = pbv[:, None] if pbv is not None else 1.0
        d = deltas * var if pbv is not None else deltas
        dcx = d[..., 0] * pw_ + pcx_
        dcy = d[..., 1] * ph_ + pcy_
        dw = np.exp(d[..., 2]) * pw_
        dh = np.exp(d[..., 3]) * ph_
        out = np.stack([dcx - dw / 2, dcy - dh / 2,
                        dcx + dw / 2 - norm, dcy + dh / 2 - norm], axis=-1)
    return Tensor(jnp.asarray(out))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 head (reference: vision/ops.py yolo_box → yolo_box op):
    returns (boxes [N, H*W*na, 4], scores [N, H*W*na, class_num])."""
    xv = np.asarray(x._value, np.float32)
    imgs = np.asarray(img_size._value if hasattr(img_size, "_value")
                      else img_size)
    N, C, H, W = xv.shape
    na = len(anchors) // 2
    an = np.asarray(anchors, np.float32).reshape(na, 2)
    if iou_aware:
        ioup = 1 / (1 + np.exp(-xv[:, :na]))
        xv = xv[:, na:]
    feat = xv.reshape(N, na, 5 + class_num, H, W)
    gx, gy = np.meshgrid(np.arange(W), np.arange(H))
    sig = lambda v: 1 / (1 + np.exp(-v))
    bx = (sig(feat[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + gx) / W
    by = (sig(feat[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + gy) / H
    input_size = downsample_ratio * H
    bw = np.exp(feat[:, :, 2]) * an[None, :, 0, None, None] / input_size
    bh = np.exp(feat[:, :, 3]) * an[None, :, 1, None, None] / input_size
    conf = sig(feat[:, :, 4])
    if iou_aware:
        conf = conf ** (1 - iou_aware_factor) * \
            ioup.reshape(N, na, H, W) ** iou_aware_factor
    cls = sig(feat[:, :, 5:]) * conf[:, :, None]
    boxes = np.zeros((N, na, H, W, 4), np.float32)
    for n in range(N):
        ih, iw = imgs[n, 0], imgs[n, 1]
        boxes[n, ..., 0] = (bx[n] - bw[n] / 2) * iw
        boxes[n, ..., 1] = (by[n] - bh[n] / 2) * ih
        boxes[n, ..., 2] = (bx[n] + bw[n] / 2) * iw
        boxes[n, ..., 3] = (by[n] + bh[n] / 2) * ih
        if clip_bbox:
            boxes[n, ..., 0::2] = boxes[n, ..., 0::2].clip(0, iw - 1)
            boxes[n, ..., 1::2] = boxes[n, ..., 1::2].clip(0, ih - 1)
    mask = conf > conf_thresh
    cls = np.where(mask[:, :, None], cls, 0.0)
    boxes = boxes.transpose(0, 1, 2, 3, 4).reshape(N, na * H * W, 4)
    scores = cls.transpose(0, 1, 3, 4, 2).reshape(N, na * H * W, class_num)
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(scores))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: vision/ops.py yolo_loss → yolov3_loss kernel):
    best-anchor assignment on host (data-dependent), box/obj/class losses on
    device so x gets gradients."""
    gt_b = np.asarray(gt_box._value, np.float32)       # (N, B, 4) cx cy w h (normalized)
    gt_l = np.asarray(gt_label._value, np.int64)       # (N, B)
    gt_s = (np.asarray(gt_score._value, np.float32) if gt_score is not None
            else (gt_b[..., 2] > 0).astype(np.float32))
    N, C, H, W = x.shape
    na = len(anchor_mask)
    an_all = np.asarray(anchors, np.float32).reshape(-1, 2)
    an = an_all[np.asarray(anchor_mask)]
    input_size = downsample_ratio * H

    # host: assign each gt to (best masked anchor, grid cell)
    tx = np.zeros((N, na, H, W), np.float32)
    ty = np.zeros_like(tx)
    tw = np.zeros_like(tx)
    th = np.zeros_like(tx)
    tobj = np.zeros_like(tx)
    tscale = np.zeros_like(tx)
    tcls = np.zeros((N, na, H, W, class_num), np.float32)
    for n in range(N):
        for b in range(gt_b.shape[1]):
            if gt_b[n, b, 2] <= 0 or gt_b[n, b, 3] <= 0:
                continue
            gw = gt_b[n, b, 2] * input_size
            gh = gt_b[n, b, 3] * input_size
            inter = np.minimum(gw, an_all[:, 0]) * np.minimum(gh, an_all[:, 1])
            iou = inter / (gw * gh + an_all[:, 0] * an_all[:, 1] - inter)
            best = int(np.argmax(iou))
            if best not in list(anchor_mask):
                continue
            k = list(anchor_mask).index(best)
            gi = min(int(gt_b[n, b, 0] * W), W - 1)
            gj = min(int(gt_b[n, b, 1] * H), H - 1)
            tx[n, k, gj, gi] = gt_b[n, b, 0] * W - gi
            ty[n, k, gj, gi] = gt_b[n, b, 1] * H - gj
            tw[n, k, gj, gi] = np.log(gw / an[k, 0])
            th[n, k, gj, gi] = np.log(gh / an[k, 1])
            tscale[n, k, gj, gi] = (2.0 - gt_b[n, b, 2] * gt_b[n, b, 3]) * \
                gt_s[n, b]
            tobj[n, k, gj, gi] = gt_s[n, b]
            lbl = int(gt_l[n, b])
            if use_label_smooth:
                # kernel semantics: on-class 1-δ, off-class δ/(C-1), δ=1/C
                delta = 1.0 / max(class_num, 1)
                if class_num > 1:
                    tcls[n, k, gj, gi, :] = delta / (class_num - 1)
                tcls[n, k, gj, gi, lbl] = 1.0 - delta
            else:
                tcls[n, k, gj, gi, lbl] = 1.0

    targets = [jnp.asarray(t) for t in
               (tx, ty, tw, th, tobj, tscale, tcls)]

    def fn(xv):
        feat = xv.reshape(N, na, 5 + class_num, H, W)
        px, py = feat[:, :, 0], feat[:, :, 1]
        pw, ph = feat[:, :, 2], feat[:, :, 3]
        pobj = feat[:, :, 4]
        pcls = jnp.moveaxis(feat[:, :, 5:], 2, -1)
        txv, tyv, twv, thv, tobjv, tscalev, tclsv = targets
        bce = lambda z, t: jnp.logaddexp(0.0, z) - t * z
        pos = tobjv > 0
        loss_xy = jnp.where(pos, tscalev * (bce(px, txv) + bce(py, tyv)), 0.0)
        loss_wh = jnp.where(
            pos, 0.5 * tscalev * ((pw - twv) ** 2 + (ph - thv) ** 2), 0.0)
        loss_obj = bce(pobj, tobjv)
        # ignore predictions overlapping any gt above ignore_thresh:
        # approximated by not penalizing positive cells twice (the kernel
        # computes pred-gt IoU; positives dominate that set)
        loss_cls = jnp.where(pos[..., None], bce(pcls, tclsv), 0.0)
        per_img = (loss_xy.sum(axis=(1, 2, 3)) + loss_wh.sum(axis=(1, 2, 3)) +
                   loss_obj.sum(axis=(1, 2, 3)) +
                   loss_cls.sum(axis=(1, 2, 3, 4)))
        return per_img
    return dispatch(fn, (x,), {}, name="yolo_loss")


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """SOLOv2 matrix NMS (reference: vision/ops.py matrix_nms → matrix_nms
    kernel): decay each box's score by its IoU with higher-scored peers."""
    bb = np.asarray(bboxes._value, np.float32)   # (N, M, 4)
    sc = np.asarray(scores._value, np.float32)   # (N, C, M)
    all_out, all_idx, rois_num = [], [], []
    N, C, M = sc.shape
    for n in range(N):
        dets = []
        idxs = []
        for c in range(C):
            if c == background_label:
                continue
            keep = np.nonzero(sc[n, c] > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[n, c, keep])][:nms_top_k]
            boxes_c = bb[n, order]
            scores_c = sc[n, c, order]
            ious = _box_iou_matrix(boxes_c, boxes_c)
            ious = np.triu(ious, 1)
            ious_cmax = ious.max(0)
            if use_gaussian:
                decay = np.exp(-(ious ** 2 - ious_cmax[None] ** 2)
                               / gaussian_sigma).min(0)
            else:
                decay = ((1 - ious) / (1 - ious_cmax[None] + 1e-10)).min(0)
            dec_scores = scores_c * decay
            m = dec_scores > post_threshold
            for i in np.nonzero(m)[0]:
                dets.append([c, dec_scores[i], *boxes_c[i]])
                idxs.append(n * M + order[i])
        if dets:
            dets = np.asarray(dets, np.float32)
            take = np.argsort(-dets[:, 1])
            if keep_top_k > 0:
                take = take[:keep_top_k]
            dets = dets[take]
            idxs = np.asarray(idxs)[take]
        else:
            dets = np.zeros((0, 6), np.float32)
            idxs = np.zeros((0,), np.int64)
        all_out.append(dets)
        all_idx.append(idxs)
        rois_num.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(all_out, 0)))
    ret = [out]
    if return_index:
        ret.append(Tensor(jnp.asarray(np.concatenate(all_idx, 0))))
    if return_rois_num:
        ret.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(ret) if len(ret) > 1 else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (reference: vision/ops.py
    distribute_fpn_proposals kernel: level = floor(log2(sqrt(area)/refer_scale
    + eps)) + refer_level)."""
    rois = np.asarray(fpn_rois._value, np.float32)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(ws * hs)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    num_lvl = max_level - min_level + 1
    multi_rois, restore = [], np.zeros(len(rois), np.int64)
    rois_num_per = []
    cursor = 0
    for L in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == L)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[sel])))
        restore[sel] = np.arange(cursor, cursor + len(sel))
        rois_num_per.append(Tensor(jnp.asarray(
            np.asarray([len(sel)], np.int32))))
        cursor += len(sel)
    restore_ind = Tensor(jnp.asarray(restore.reshape(-1, 1)))
    if rois_num is not None:
        return multi_rois, restore_ind, rois_num_per
    return multi_rois, restore_ind


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference: vision/ops.py generate_proposals
    kernel): decode → clip → filter → NMS, per image."""
    sc = np.asarray(scores._value, np.float32)        # (N, A, H, W)
    bd = np.asarray(bbox_deltas._value, np.float32)   # (N, 4A, H, W)
    ims = np.asarray(img_size._value, np.float32)     # (N, 2)
    anc = np.asarray(anchors._value if hasattr(anchors, "_value")
                     else anchors, np.float32).reshape(-1, 4)
    var = np.asarray(variances._value if hasattr(variances, "_value")
                     else variances, np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    out_rois, out_probs, out_num = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).ravel()
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], anc[order % len(anc)], \
            var[order % len(var)]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        dcx = v[:, 0] * d[:, 0] * aw + acx
        dcy = v[:, 1] * d[:, 1] * ah + acy
        dw = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        dh = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        props = np.stack([dcx - dw / 2, dcy - dh / 2,
                          dcx + dw / 2 - off, dcy + dh / 2 - off], -1)
        ih, iw = ims[n]
        props[:, 0::2] = props[:, 0::2].clip(0, iw - off)
        props[:, 1::2] = props[:, 1::2].clip(0, ih - off)
        keep = np.nonzero((props[:, 2] - props[:, 0] + off >= min_size) &
                          (props[:, 3] - props[:, 1] + off >= min_size))[0]
        props, s = props[keep], s[keep]
        # nms
        sel = []
        order2 = np.argsort(-s)
        while order2.size and len(sel) < post_nms_top_n:
            i = order2[0]
            sel.append(i)
            if order2.size == 1:
                break
            ious = _box_iou_matrix(props[i:i + 1], props[order2[1:]])[0]
            order2 = order2[1:][ious <= nms_thresh]
        out_rois.append(props[sel])
        out_probs.append(s[sel])
        out_num.append(len(sel))
    rois = Tensor(jnp.asarray(np.concatenate(out_rois, 0)))
    probs = Tensor(jnp.asarray(np.concatenate(out_probs, 0)[:, None]))
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(out_num, np.int32)))
    return rois, probs


def read_file(filename, name=None):
    """reference: vision/ops.py read_file — file bytes as a uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """reference: vision/ops.py decode_jpeg (nvjpeg) — host PIL decode to a
    CHW uint8 tensor."""
    import io
    from PIL import Image
    data = bytes(np.asarray(x._value, np.uint8).tobytes())
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
