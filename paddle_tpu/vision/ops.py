"""paddle.vision.ops analog — detection/vision operators.

Reference: python/paddle/vision/ops.py (nms, roi_align:1130, roi_pool,
box_coder, deform_conv2d, distribute_fpn_proposals, PSRoIPool). TPU-native:
RoI ops are bilinear gathers (XLA gather HLO); NMS is a lax.fori-style
suppression over a statically-shaped score ordering (no dynamic shapes inside
jit); deform_conv2d assembles its sampling grid with vectorized gathers.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch

__all__ = ["nms", "roi_align", "roi_pool", "box_iou", "deform_conv2d",
           "PSRoIPool", "psroi_pool", "DeformConv2D", "RoIAlign", "RoIPool"]


def _box_iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU (M, N) for xyxy boxes."""
    return dispatch(_box_iou_matrix, (boxes1, boxes2), {}, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS returning kept indices sorted by score.

    Reference: vision/ops.py nms. Static-shape friendly: the suppression loop
    is a lax.fori_loop over the fixed box count, so it jit-compiles.
    """
    n = int(boxes.shape[0])

    def fn(bx, sc, cat):
        order = jnp.argsort(-sc) if sc is not None \
            else jnp.arange(n)
        b_sorted = bx[order]
        iou = _box_iou_matrix(b_sorted, b_sorted)
        if cat is not None:
            c_sorted = cat[order]
            same = c_sorted[:, None] == c_sorted[None, :]
            iou = jnp.where(same, iou, 0.0)  # cross-category never suppresses

        def body(i, keep):
            # i suppressed already? then it can't suppress others
            sup = (iou[i] > iou_threshold) & keep[i]
            sup = sup & (jnp.arange(n) > i)  # only later (lower-score) boxes
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        return order, keep

    sc_val = scores
    order_t, keep_t = dispatch(fn, (boxes, sc_val, category_idxs), {},
                               name="nms")
    order = np.asarray(order_t._value)
    keep = np.asarray(keep_t._value)
    kept = order[keep]
    if top_k is not None:
        kept = kept[:top_k]
    from ..ops.creation import to_tensor
    return to_tensor(kept.astype(np.int64))


def _bilinear_sample(feat, ys, xs):
    """feat: (C, H, W); ys/xs arbitrary same-shape float coords."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = ys - y0
    wx1 = xs - x0
    wy0, wx0 = 1 - wy1, 1 - wx1

    def at(yi, xi):
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = feat[:, yc, xc]  # (C, ...)
        return jnp.where(valid, v, 0.0)

    return (at(y0, x0) * wy0 * wx0 + at(y0, x1) * wy0 * wx1
            + at(y1, x0) * wy1 * wx0 + at(y1, x1) * wy1 * wx1)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference: vision/ops.py:1130). boxes: (R, 4) xyxy in input
    coords; boxes_num: per-image box counts."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    nums = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                      else boxes_num).astype(np.int64)
    img_ids = jnp.asarray(np.repeat(np.arange(len(nums)), nums))
    if sampling_ratio > 0:
        ratio = sampling_ratio
    else:
        # adaptive (reference: ceil(roi_size / pooled_size) per RoI). Static
        # shapes require one grid, so use the max needed ratio across the
        # (host-resident) boxes, capped to keep the gather bounded.
        try:
            bx_np = np.asarray(boxes._value if isinstance(boxes, Tensor)
                               else boxes, dtype=np.float64)
            rh = (bx_np[:, 3] - bx_np[:, 1]) * spatial_scale / output_size[0]
            rw = (bx_np[:, 2] - bx_np[:, 0]) * spatial_scale / output_size[1]
            ratio = int(min(max(np.ceil(max(rh.max(), rw.max(), 1.0)), 1), 8))
        except Exception:  # traced boxes under jit — fixed fallback
            ratio = 2

    def fn(feat, bx):
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sample grid: (R, ph, ratio) x (R, pw, ratio)
        iy = (jnp.arange(ph)[None, :, None]
              + (jnp.arange(ratio)[None, None, :] + 0.5) / ratio)
        ix = (jnp.arange(pw)[None, :, None]
              + (jnp.arange(ratio)[None, None, :] + 0.5) / ratio)
        ys = y1[:, None, None] + iy * bin_h[:, None, None]   # (R, ph, r)
        xs = x1[:, None, None] + ix * bin_w[:, None, None]   # (R, pw, r)

        def per_roi(img_id, ys_r, xs_r):
            feat_i = feat[img_id]
            yy = ys_r[:, :, None, None]                       # (ph, r, 1, 1)
            xx = xs_r[None, None, :, :]                       # (1, 1, pw, r)
            yy = jnp.broadcast_to(yy, (ph, ratio, pw, ratio))
            xx = jnp.broadcast_to(xx, (ph, ratio, pw, ratio))
            vals = _bilinear_sample(feat_i, yy, xx)           # (C, ph,r,pw,r)
            return vals.mean(axis=(2, 4))                     # (C, ph, pw)

        return jax.vmap(per_roi)(img_ids, ys, xs)

    return dispatch(fn, (x, boxes), {}, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool: max over quantized bins (reference: vision/ops.py roi_pool)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    nums = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                      else boxes_num).astype(np.int64)
    img_ids = jnp.asarray(np.repeat(np.arange(len(nums)), nums))

    def fn(feat, bx):
        H, W = feat.shape[-2], feat.shape[-1]
        x1 = jnp.round(bx[:, 0] * spatial_scale)
        y1 = jnp.round(bx[:, 1] * spatial_scale)
        x2 = jnp.round(bx[:, 2] * spatial_scale)
        y2 = jnp.round(bx[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        yy = jnp.arange(H, dtype=jnp.float32)
        xx = jnp.arange(W, dtype=jnp.float32)

        def per_roi(img_id, px1, py1, bh, bw):
            feat_i = feat[img_id]  # (C, H, W)
            # bin membership masks per output cell (static shapes)
            ys0 = py1 + jnp.arange(ph) * bh
            ys1 = py1 + (jnp.arange(ph) + 1) * bh
            xs0 = px1 + jnp.arange(pw) * bw
            xs1 = px1 + (jnp.arange(pw) + 1) * bw
            ymask = (yy[None, :] >= jnp.floor(ys0)[:, None]) \
                & (yy[None, :] < jnp.ceil(ys1)[:, None])      # (ph, H)
            xmask = (xx[None, :] >= jnp.floor(xs0)[:, None]) \
                & (xx[None, :] < jnp.ceil(xs1)[:, None])      # (pw, W)
            m = ymask[:, None, :, None] & xmask[None, :, None, :]
            big = jnp.where(m[None], feat_i[:, None, None, :, :], -jnp.inf)
            out = big.max(axis=(-2, -1))                      # (C, ph, pw)
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(per_roi)(img_ids, x1, y1, bin_h, bin_w)

    return dispatch(fn, (x, boxes), {}, name="roi_pool")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference: vision/ops.py psroi_pool):
    input channels C = out_c * ph * pw; cell (i, j) pools its own channel
    group, average-pooled."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    nums = np.asarray(boxes_num._value if isinstance(boxes_num, Tensor)
                      else boxes_num).astype(np.int64)
    img_ids = jnp.asarray(np.repeat(np.arange(len(nums)), nums))

    def fn(feat, bx):
        C = feat.shape[1]
        out_c = C // (ph * pw)
        x1 = bx[:, 0] * spatial_scale
        y1 = bx[:, 1] * spatial_scale
        x2 = bx[:, 2] * spatial_scale
        y2 = bx[:, 3] * spatial_scale
        bin_h = jnp.maximum(y2 - y1, 0.1) / ph
        bin_w = jnp.maximum(x2 - x1, 0.1) / pw
        ratio = 2

        def per_roi(img_id, px1, py1, bh, bw):
            feat_i = feat[img_id].reshape(out_c, ph, pw, *feat.shape[-2:])
            iy = (jnp.arange(ph)[:, None]
                  + (jnp.arange(ratio)[None, :] + 0.5) / ratio)
            ix = (jnp.arange(pw)[:, None]
                  + (jnp.arange(ratio)[None, :] + 0.5) / ratio)
            ys = py1 + iy * bh                                  # (ph, r)
            xs = px1 + ix * bw                                  # (pw, r)
            cells = []
            for i in range(ph):
                row = []
                for j in range(pw):
                    yy = jnp.broadcast_to(ys[i][:, None], (ratio, ratio))
                    xx = jnp.broadcast_to(xs[j][None, :], (ratio, ratio))
                    v = _bilinear_sample(feat_i[:, i, j], yy, xx)
                    row.append(v.mean(axis=(-2, -1)))           # (out_c,)
                cells.append(jnp.stack(row, axis=-1))           # (out_c, pw)
            return jnp.stack(cells, axis=-2)                    # (out_c,ph,pw)

        return jax.vmap(per_roi)(img_ids, x1, y1, bin_h, bin_w)

    return dispatch(fn, (x, boxes), {}, name="psroi_pool")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference: vision/ops.py deform_conv2d).

    offset: (N, 2 * dg * kh * kw, Hout, Wout); mask (v2): (N, dg*kh*kw, ...).
    Implementation: bilinear-gather the deformed sampling grid into an im2col
    tensor, then one big matmul — the MXU-friendly formulation.
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(xv, off, w, m, b):
        N, C, H, W = xv.shape
        out_ch, in_per_g, kh, kw = w.shape
        Hout = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        Wout = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        dg = deformable_groups
        off = off.reshape(N, dg, kh * kw, 2, Hout, Wout)
        base_y = jnp.arange(Hout) * stride[0] - padding[0]    # (Hout,)
        base_x = jnp.arange(Wout) * stride[1] - padding[1]    # (Wout,)
        ky_full = jnp.repeat(jnp.arange(kh) * dilation[0], kw)  # (kh*kw,)
        kx_full = jnp.tile(jnp.arange(kw) * dilation[1], kh)    # (kh*kw,)
        grid_y = base_y[None, :, None] + ky_full[:, None, None]  # (khkw,Ho,1)
        grid_x = base_x[None, None, :] + kx_full[:, None, None]  # (khkw,1,Wo)

        def per_image(xi, offi, mi):
            cols = []
            c_per_dg = C // dg
            for g in range(dg):
                ys = grid_y + offi[g, :, 0]                  # (khkw,Hout,Wout)
                xs = grid_x + offi[g, :, 1]
                feat = xi[g * c_per_dg:(g + 1) * c_per_dg]
                v = _bilinear_sample(feat, ys, xs)           # (c, khkw, Ho,Wo)
                if mi is not None:
                    v = v * mi[g][None]
                cols.append(v)
            col = jnp.concatenate(cols, axis=0)              # (C, khkw, Ho,Wo)
            return col

        if m is not None:
            mi = m.reshape(N, dg, kh * kw, Hout, Wout)
            col = jax.vmap(per_image)(xv, off, mi)
        else:
            col = jax.vmap(lambda a, o: per_image(a, o, None))(xv, off)
        # (N, C, khkw, Ho, Wo) x w(out, C/g, kh, kw)
        col = col.reshape(N, groups, C // groups, kh * kw, Hout * Wout)
        wg = w.reshape(groups, out_ch // groups, in_per_g * kh * kw)
        col2 = col.reshape(N, groups, (C // groups) * kh * kw, Hout * Wout)
        out = jnp.einsum("goi,ngiw->ngow", wg, col2)
        out = out.reshape(N, out_ch, Hout, Wout)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    return dispatch(fn, (x, offset, weight, mask, bias), {},
                    name="deform_conv2d")


# ---------------------------------------------------------------------------
# layer wrappers
# ---------------------------------------------------------------------------

from ..nn.layer_base import Layer  # noqa: E402


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._args[0],
                         spatial_scale=self._args[1])


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._args[0],
                        spatial_scale=self._args[1])


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._args[0],
                          spatial_scale=self._args[1])


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = (stride, padding, dilation, deformable_groups, groups)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + k, attr=weight_attr)
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, offset, mask=None):
        stride, padding, dilation, dg, groups = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias, stride,
                             padding, dilation, dg, groups, mask)
