"""paddle.static.nn — static-graph layer functions.

Reference: python/paddle/static/nn/__init__.py — the static layer API is
the same compute as the dygraph layers; the program tape records whatever
ops they dispatch (see static/__init__.py design note).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def fc(x, size, num_flatten_dims=1, activation=None, name=None):
    from ..nn.layer.common import Linear
    from ..nn import functional as F
    from .. import ops
    # paddle semantics: flatten dims [num_flatten_dims:] into the
    # projected axis (base/layers fc)
    if num_flatten_dims != len(x.shape) - 1:
        x = ops.flatten(x, start_axis=num_flatten_dims)
    lin = Linear(x.shape[-1], size)
    out = lin(x)
    if activation:
        out = getattr(F, activation)(out)
    return out

def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, act=None, name=None, **kwargs):
    from ..nn.layer.conv import Conv2D
    from ..nn import functional as F
    conv = Conv2D(input.shape[1], num_filters, filter_size, stride,
                  padding, dilation, groups)
    out = conv(input)
    if act:
        out = getattr(F, act)(out)
    return out

def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               data_layout="NCHW", name=None, **kwargs):
    from ..nn.layer.norm import BatchNorm2D
    from ..nn import functional as F
    ch_axis = 1 if data_layout == "NCHW" else -1
    bn = BatchNorm2D(input.shape[ch_axis], momentum=momentum,
                     epsilon=epsilon, data_format=data_layout)
    if is_test:
        bn.eval()
    out = bn(input)
    if act:
        out = getattr(F, act)(out)
    return out

def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, name=None, **kwargs):
    from ..nn.layer.common import Embedding
    return Embedding(size[0], size[1], padding_idx=padding_idx)(input)

def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, act=None, name=None, **kwargs):
    from ..nn import functional as F
    shape = input.shape[begin_norm_axis:]
    # affine-less LN equals ones/zeros affine — skip the constant tensors
    out = F.layer_norm(input, shape, weight=None, bias=None,
                       epsilon=epsilon)
    if act:
        out = getattr(F, act)(out)
    return out

def dropout(x, dropout_prob=0.5, is_test=False, name=None, **kwargs):
    from ..nn import functional as F
    return F.dropout(x, p=dropout_prob, training=not is_test)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, act=None, name=None, **kwargs):
    from ..nn.layer.conv import Conv3D
    from ..nn import functional as F
    out = Conv3D(input.shape[1], num_filters, filter_size, stride, padding,
                 dilation, groups)(input)
    return getattr(F, act)(out) if act else out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1, act=None,
                     name=None, **kwargs):
    from ..nn.layer.conv import Conv2DTranspose
    from ..nn import functional as F
    out = Conv2DTranspose(input.shape[1], num_filters, filter_size, stride,
                          padding, dilation=dilation, groups=groups)(input)
    return getattr(F, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1, act=None,
                     name=None, **kwargs):
    from ..nn.layer.conv import Conv3DTranspose
    from ..nn import functional as F
    out = Conv3DTranspose(input.shape[1], num_filters, filter_size, stride,
                          padding, dilation=dilation, groups=groups)(input)
    return getattr(F, act)(out) if act else out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from ..nn.layer.norm import GroupNorm
    from ..nn import functional as F
    out = GroupNorm(groups, input.shape[1], epsilon=epsilon)(input)
    return getattr(F, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn.layer.norm import InstanceNorm2D
    return InstanceNorm2D(input.shape[1], epsilon=epsilon)(input)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """reference: static/nn/common.py prelu — alpha shape by mode
    (all/channel/element)."""
    import paddle_tpu as _paddle
    from ..nn import functional as F
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1] if data_format == "NCHW" else x.shape[-1]]
    elif mode == "element":
        shape = list(x.shape[1:])
    else:
        raise ValueError("mode should be one of 'all', 'channel', 'element'")
    from ..nn.initializer import Constant
    alpha = _paddle.create_parameter(shape, "float32", attr=param_attr,
                                     default_initializer=Constant(0.25))
    return F.prelu(x, alpha, data_format=data_format)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    from ..nn.layer.common import Bilinear
    from ..nn import functional as F
    out = Bilinear(x.shape[-1], y.shape[-1], size)(x, y)
    return getattr(F, act)(out) if act else out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn.layer.norm import SpectralNorm
    return SpectralNorm(list(weight.shape), dim=dim, power_iters=power_iters,
                        epsilon=eps)(weight)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    import paddle_tpu as _paddle
    from ..vision.ops import deform_conv2d as _dc
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    w = _paddle.create_parameter(
        [num_filters, x.shape[1] // groups, k[0], k[1]], "float32",
        attr=param_attr)
    return _dc(x, offset, w, mask=mask, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS sparse table embedding (reference: static/nn/common.py
    sparse_embedding). Dense fallback on TPU; the PS path lives in
    incubate.distributed.ps."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_rate=0.9999999, sync_stats=False,
              enable_scale_and_shift=False):
    """CTR data normalization (reference: static/nn/common.py data_norm → phi
    data_norm kernel): normalize by accumulated batch summaries
    mean = batch_sum/batch_size, scale = rsqrt(batch_square_sum/batch_size)."""
    import paddle_tpu as _paddle
    from ..nn.initializer import Constant
    C = input.shape[-1] if data_layout == "NHWC" else input.shape[1]
    batch_size = _paddle.create_parameter([C], "float32",
                                          default_initializer=Constant(1e4))
    batch_sum = _paddle.create_parameter([C], "float32",
                                         default_initializer=Constant(0.0))
    batch_square_sum = _paddle.create_parameter(
        [C], "float32", default_initializer=Constant(1e4))
    mean = batch_sum / batch_size
    scale = (batch_size / batch_square_sum) ** 0.5
    out = (input - mean) * scale
    from ..nn import functional as F
    return getattr(F, act)(out) if act else out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=5, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference: static/nn/common.py nce →
    nce op): binary logistic on the true class vs sampled noise classes.
    Returns per-sample loss [N, 1]."""
    import paddle_tpu as _paddle
    import numpy as _np
    from ..core import random as _random
    dim = input.shape[-1]
    w = _paddle.create_parameter([num_total_classes, dim], "float32",
                                 attr=param_attr)
    b = _paddle.create_parameter([num_total_classes], "float32",
                                 attr=bias_attr, is_bias=True)
    key = _random.next_key()
    if sampler == "uniform":
        noise = jax.random.randint(key, (num_neg_samples,), 0,
                                   num_total_classes)
        logq = jnp.full((num_neg_samples,),
                        -_np.log(num_total_classes), jnp.float32)
    elif sampler == "custom_dist":
        probs = jnp.asarray(custom_dist, jnp.float32)
        noise = jax.random.categorical(
            key, jnp.log(probs + 1e-20), shape=(num_neg_samples,))
        logq = jnp.log(probs[noise] + 1e-20)
    else:  # log_uniform
        u = jax.random.uniform(key, (num_neg_samples,))
        noise = (jnp.exp(u * _np.log(num_total_classes + 1)) - 1).astype(
            jnp.int32)
        noise = jnp.clip(noise, 0, num_total_classes - 1)
        logq = jnp.log((jnp.log(noise + 2.0) - jnp.log(noise + 1.0))
                       / _np.log(num_total_classes + 1))

    def fn(x, lbl, wv, bv):
        lbl = lbl.reshape(-1)
        pos_logit = jnp.sum(x * wv[lbl], -1) + bv[lbl]
        pos_loss = jnp.logaddexp(0.0, -pos_logit)  # -log sigmoid(s)
        neg_logit = x @ wv[noise].T + bv[noise]    # (N, k)
        neg_loss = jnp.sum(jnp.logaddexp(0.0, neg_logit), -1)
        return (pos_loss + neg_loss)[:, None]
    from ..core.tensor import dispatch as _dispatch
    return _dispatch(fn, (input, label, w, b), {}, name="nce")


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference: static/nn/common.py row_conv →
    phi row_conv kernel): out[t] = sum_{i=0..k} x[t+i] * w[i], per feature."""
    import paddle_tpu as _paddle
    from ..nn import functional as F
    D = input.shape[-1]
    k = future_context_size
    w = _paddle.create_parameter([k + 1, D], "float32", attr=param_attr)

    def fn(x, wv):
        pad = [(0, 0)] * x.ndim
        pad[-2] = (0, k)
        xp = jnp.pad(x, pad)
        out = 0.0
        for i in range(k + 1):
            sl = [slice(None)] * x.ndim
            sl[-2] = slice(i, i + x.shape[-2])
            out = out + xp[tuple(sl)] * wv[i]
        return out
    from ..core.tensor import dispatch as _dispatch
    out = _dispatch(fn, (input, w), {}, name="row_conv")
    return getattr(F, act)(out) if act else out


# -- control flow (host-evaluated in the eager-tape model) -------------------

def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """reference: static/nn/control_flow.py cond. Eager: pred is concrete, so
    this is host branching (the jit path uses lax.cond via paddle_tpu.jit)."""
    import numpy as _np
    taken = bool(_np.asarray(pred._value if hasattr(pred, "_value") else pred))
    if taken:
        return true_fn() if true_fn is not None else None
    return false_fn() if false_fn is not None else None


def case(pred_fn_pairs, default=None, name=None):
    """reference: control_flow.py case — first true predicate wins."""
    import numpy as _np
    for pred, fn in pred_fn_pairs:
        if bool(_np.asarray(pred._value if hasattr(pred, "_value") else pred)):
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference: control_flow.py switch_case."""
    import numpy as _np
    idx = int(_np.asarray(branch_index._value
                          if hasattr(branch_index, "_value") else branch_index))
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    if idx in fns:
        return fns[idx]()
    if default is not None:
        return default()
    return fns[max(fns)]()


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """reference: control_flow.py while_loop. Eager host loop; the traced path
    is lax.while_loop inside jit."""
    import numpy as _np
    vars_ = list(loop_vars)
    while bool(_np.asarray(cond(*vars_)._value)):
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """reference: control_flow.py static_pylayer — custom fwd/bwd pair."""
    from ..autograd import PyLayer
    from ..core.tensor import Tensor as _T

    if backward_fn is None:
        outs = forward_fn(*inputs)
        return outs

    class _SP(PyLayer):
        @staticmethod
        def forward(ctx, *xs):
            return forward_fn(*xs)

        @staticmethod
        def backward(ctx, *gs):
            return backward_fn(*gs)

    return _SP.apply(*inputs)


# -- sequence ops on padded [B, T, D] tensors --------------------------------
# The reference operates on LoD (ragged) tensors; the TPU-native layout is
# padded-dense (static shapes for XLA), so these reduce over the time axis.

def sequence_softmax(input, use_cudnn=False, name=None):
    from ..nn import functional as F
    return F.softmax(input, axis=1)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    import paddle_tpu as _paddle
    pt = pool_type.lower()
    if pt == "max":
        return _paddle.max(input, axis=1)
    if pt in ("average", "avg"):
        return _paddle.mean(input, axis=1)
    if pt == "sum":
        return _paddle.sum(input, axis=1)
    if pt == "sqrt":
        T = input.shape[1]
        return _paddle.sum(input, axis=1) / float(T) ** 0.5
    if pt == "first":
        return input[:, 0]
    if pt == "last":
        return input[:, -1]
    raise ValueError(f"unsupported pool_type {pool_type}")


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_expand(x, y, ref_level=-1, name=None):
    """Padded-dense analog: broadcast x rows to y's time length."""
    import paddle_tpu as _paddle
    reps = y.shape[1] if y.ndim > 1 else 1
    return _paddle.concat([x] * reps, axis=0) if x.ndim == 2 else x


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window conv over time (reference: sequence_conv op): for each
    t, concat the window rows and project."""
    import paddle_tpu as _paddle
    from ..nn import functional as F
    D = input.shape[-1]
    w = _paddle.create_parameter([filter_size * D, num_filters], "float32",
                                 attr=param_attr)

    def fn(x, wv):
        start = padding_start if padding_start is not None \
            else -(filter_size // 2)
        cols = []
        T = x.shape[1]
        for i in range(filter_size):
            shift = start + i
            if shift < 0:
                seg = jnp.pad(x[:, :T + shift], ((0, 0), (-shift, 0), (0, 0)))
            elif shift > 0:
                seg = jnp.pad(x[:, shift:], ((0, 0), (0, shift), (0, 0)))
            else:
                seg = x
            cols.append(seg)
        ctx = jnp.concatenate(cols, axis=-1)
        return ctx @ wv
    from ..core.tensor import dispatch as _dispatch
    out = _dispatch(fn, (input, w), {}, name="sequence_conv")
    return getattr(F, act)(out) if act else out


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: static/nn/common.py py_func — re-exported from static."""
    from . import py_func as _py_func
    return _py_func(func, x, out, backward_func, skip_vars_in_backward_input)
