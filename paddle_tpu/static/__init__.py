"""paddle.static analog — deferred-execution graph API over the eager tape.

Reference: python/paddle/static/ (Program/Executor/data, SURVEY.md §2.6) where
a Program is a protobuf op graph executed by the C++ PirInterpreter.

TPU-native redesign: there is no separate graph IR — the eager tape (core/
tensor.py Node DAG, each node carrying a pure `fwd_fn`) IS the captured
program. `static.data` creates named placeholder tensors; building ops under
`program_guard` records the tape; `Executor.run(prog, feed, fetch_list)`
REPLAYS the tape DAG with feed values substituted at the placeholders,
compiled once per (feed shapes, fetches) signature with jax.jit — the analog
of PirInterpreter's first-run lowering + cached instruction list. Training
loops belong to the dygraph/jit path (TrainStep); the static surface covers
graph capture, feed/fetch execution, and save/load_inference_model.
"""
from __future__ import annotations

import contextlib
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes

__all__ = [
    "Program", "program_guard", "default_main_program", "default_startup_program",
    "data", "InputSpec", "Executor", "save_inference_model",
    "load_inference_model", "name_scope", "nn", "append_backward", "gradients",
    "global_scope", "scope_guard", "Scope", "BuildStrategy", "CompiledProgram",
    "ExecutionStrategy", "Print", "py_func", "WeightNormParamAttr",
    "ExponentialMovingAverage", "save", "load", "serialize_program",
    "serialize_persistables", "save_to_file", "deserialize_program",
    "deserialize_persistables", "load_from_file", "normalize_program",
    "load_program_state", "set_program_state", "cpu_places", "cuda_places",
    "xpu_places", "Variable", "create_global_var", "create_parameter",
    "accuracy", "auc", "device_guard", "ipu_shard_guard", "IpuCompiledProgram",
    "IpuStrategy", "set_ipu_shard", "ctr_metric_bundle",
]


class Program:
    """Captured-graph container: tracks placeholders + fetch targets created
    in its guard scope (reference: base/framework.py Program:5890)."""

    def __init__(self):
        self.placeholders = {}
        self.random_seed = None
        self._tensors = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def __repr__(self):
        return (f"Program(placeholders={list(self.placeholders)}, "
                f"tensors={len(self._tensors)})")


_default_main = Program()
_default_startup = Program()
_prog_stack = [_default_main]


def default_main_program():
    return _prog_stack[-1]


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _prog_stack.append(main_program)
    try:
        yield
    finally:
        _prog_stack.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    with jax.named_scope(prefix or "scope"):
        yield


class InputSpec:
    """Shape/dtype spec (reference: static/input.py InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference: static/input.py data). Returns a zero
    Tensor tagged with the feed name; -1 dims become 1 at trace time and are
    re-specialized per feed shape at Executor.run."""
    shp = [1 if (d is None or d < 0) else int(d) for d in shape]
    t = Tensor(jnp.zeros(shp, dtypes.convert_dtype(dtype)), stop_gradient=False)
    t.name = name
    t._feed_name = name
    default_main_program().placeholders[name] = t
    return t


def _replay(fetch_leaf_tensors, feed_values):
    """Recompute fetch values by walking the tape DAG, substituting feeds.

    feed_values: {feed_name: jax value}. Pure: usable under jax.jit.
    """
    node_memo = {}

    def tensor_value(t):
        fname = getattr(t, "_feed_name", None)
        if fname is not None and fname in feed_values:
            return feed_values[fname]
        node = t._node
        if node is None:
            return t._value
        leaves = node_leaves(node)
        return leaves[t._out_index]

    def node_leaves(node):
        got = node_memo.get(id(node))
        if got is not None:
            return got
        ins = [tensor_value(p) for p in node.parents]
        out = node.fwd_fn(*ins)
        leaves = jax.tree_util.tree_flatten(out)[0]
        node_memo[id(node)] = leaves
        return leaves

    return [tensor_value(t) for t in fetch_leaf_tensors]


class Executor:
    """Feed/fetch executor over captured graphs (reference: base/executor.py
    Executor:1237 -> StandaloneExecutor). jit-compiles the replay per
    (fetches, feed signature) and caches the executable."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        feed = feed or {}
        fetch_list = fetch_list or []
        fetches = [f for f in fetch_list]
        for f in fetches:
            if not isinstance(f, Tensor):
                raise TypeError(f"fetch_list entries must be Tensors, got {f!r}")
        feed_vals = {k: jnp.asarray(v._value if isinstance(v, Tensor) else v)
                     for k, v in feed.items()}
        key = (tuple(id(f) for f in fetches),
               tuple(sorted((k, v.shape, str(v.dtype))
                            for k, v in feed_vals.items())))
        fn = self._cache.get(key)
        if fn is None:
            names = sorted(feed_vals)

            def run_fn(*vals):
                return _replay(fetches, dict(zip(names, vals)))
            fn = jax.jit(run_fn)
            self._cache[key] = (fn, names)
        fn, names = self._cache[key]
        outs = fn(*[feed_vals[n] for n in names])
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o, stop_gradient=True) for o in outs]

    def close(self):
        self._cache.clear()


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize a captured graph (reference: static/io.py save_inference_model).

    TPU-native: stores the REPLAY CLOSURE's jaxpr-equivalent by re-tracing the
    fetches as a function of the feeds, plus all captured constants, with
    pickle of the jitted function's inputs — practically: we store feed specs
    and the fetch values' computation via jax.export when available, else the
    feed/fetch tensors for same-process reuse."""
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = (fetch_vars if isinstance(fetch_vars, (list, tuple))
                  else [fetch_vars])
    names = [getattr(v, "_feed_name", getattr(v, "name", None))
             for v in feed_vars]

    def fn(*vals):
        return _replay(fetch_vars, dict(zip(names, vals)))

    args = [jnp.zeros(v.shape, v._value.dtype) for v in feed_vars]
    payload = {"feed_names": names,
               "feed_specs": [(v.shape, str(np.dtype(v.dtype))) for v in feed_vars],
               "fetch_names": [getattr(v, "name", None) or f"fetch_{i}"
                               for i, v in enumerate(fetch_vars)]}
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    try:
        from jax import export as jax_export
        exported = jax_export.export(jax.jit(fn))(
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args])
        payload["serialized"] = exported.serialize()
        payload["format"] = "jax_export"
    except Exception:
        outs = fn(*args)
        payload["format"] = "none"
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(payload, f)
    return path_prefix + ".pdmodel"


def load_inference_model(path_prefix, executor=None, _return_meta=False,
                         **kwargs):
    """Load a saved inference graph; returns (program, feed_names, fetch_fn),
    or (fetch_fn, payload_meta) when _return_meta=True (paddle.inference path)."""
    path = path_prefix
    if not path.endswith(".pdmodel"):
        path = path_prefix + ".pdmodel"
    with open(path, "rb") as f:
        payload = pickle.load(f)
    names = payload["feed_names"]
    if payload.get("format") == "jax_export":
        from jax import export as jax_export
        exported = jax_export.deserialize(payload["serialized"])

        def fetch_fn(*vals):
            return exported.call(*[jnp.asarray(v) for v in vals])

        if _return_meta:
            return fetch_fn, payload
        return Program(), names, fetch_fn
    raise RuntimeError("model was saved without jax.export support")

from . import nn  # noqa: E402,F401


# ---------------------------------------------------------------------------
# Program state: parameters, scopes, save/load (reference: static/io.py,
# base/executor.py global_scope)
# ---------------------------------------------------------------------------

Variable = Tensor  # the static Variable IS a Tensor here (one tensor model)


class _ScopeVar:
    def __init__(self, value=None):
        self._value = value

    def get_tensor(self):
        return self

    def set(self, value, place=None):
        self._value = np.asarray(value)

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype else arr


class Scope:
    """Name → variable map (reference: framework Scope, scope.h:50)."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, _ScopeVar())

    def find_var(self, name):
        return self._vars.get(name)

    def local_scope(self):
        return Scope()


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference: static (create_parameter) — registers into the current
    Program so static.save can find it."""
    import paddle_tpu as _paddle
    p = _paddle.create_parameter(shape, dtype, name=name, attr=attr,
                                 is_bias=is_bias,
                                 default_initializer=default_initializer)
    prog = default_main_program()
    prog._parameters = getattr(prog, "_parameters", {})
    prog._parameters[p.name or f"param_{len(prog._parameters)}"] = p
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    t = Tensor(jnp.full(tuple(shape), value, dtypes.convert_dtype(dtype)),
               stop_gradient=True)
    t.name = name
    t.persistable = persistable
    prog = default_main_program()
    prog._parameters = getattr(prog, "_parameters", {})
    prog._parameters[name or f"var_{len(prog._parameters)}"] = t
    return t


def _program_state(program):
    params = getattr(program or default_main_program(), "_parameters", {})
    return {k: np.asarray(v._value) for k, v in params.items()}


def save(program, model_path, protocol=4):
    """reference: static/io.py save — persistables of the program."""
    state = _program_state(program)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    return model_path + ".pdparams"


def load(program, model_path, executor=None, var_list=None):
    """reference: static/io.py load."""
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)
    return state


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    params = getattr(program or default_main_program(), "_parameters", {})
    for k, p in params.items():
        if k in state_dict:
            p._value = jnp.asarray(state_dict[k], p._value.dtype)


def serialize_program(feed_vars, fetch_vars, **kwargs):
    """reference: static/io.py serialize_program — bytes of the graph."""
    import pickle as _pickle
    names = [getattr(v, "_feed_name", getattr(v, "name", None))
             for v in (feed_vars if isinstance(feed_vars, (list, tuple))
                       else [feed_vars])]
    return _pickle.dumps({"feed_names": names})


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    return pickle.dumps(_program_state(default_main_program()))


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    meta = pickle.loads(data)
    prog = Program()
    prog._meta = meta
    return prog


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    set_program_state(program, state)
    return state


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference: static/io.py normalize_program — prune to the feed→fetch
    slice. The tape replay already computes only the fetch closure, so the
    program passes through."""
    return program


# ---------------------------------------------------------------------------
# Autograd on the captured tape (reference: base/backward.py)
# ---------------------------------------------------------------------------

def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference: base/backward.py append_backward — returns
    [(param, grad_var)] pairs."""
    from ..autograd.backward import grad as _grad
    if parameter_list is None:
        # reference resolves params from loss.block.program; our tape IS the
        # program, so walk the loss's autograd graph for Parameter leaves
        # (works outside program_guard too), falling back to the registry.
        from ..nn.layer_base import Parameter
        found, seen, stack = [], set(), [loss]
        while stack:
            t = stack.pop()
            node = getattr(t, "_node", None)
            if isinstance(t, Parameter) and id(t) not in seen:
                seen.add(id(t))
                found.append(t)
            if node is not None and id(node) not in seen:
                seen.add(id(node))
                stack.extend(node.parents)
        prog = default_main_program()
        registry = list(getattr(prog, "_parameters", {}).values())
        parameter_list = found or registry
    parameter_list = [p for p in parameter_list if not p.stop_gradient]
    grads = _grad([loss], parameter_list, retain_graph=True,
                  allow_unused=True)
    return list(zip(parameter_list, grads))


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: base/backward.py gradients."""
    from ..autograd.backward import grad as _grad
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _grad(list(targets), list(inputs), grad_outputs=target_gradients,
                 retain_graph=True, allow_unused=True)


# ---------------------------------------------------------------------------
# Execution config + devices (XLA owns the pass pipeline; these are contracts)
# ---------------------------------------------------------------------------

class BuildStrategy:
    """reference: pybind BuildStrategy — graph-pass knobs. XLA performs the
    fusion/memory passes; flags are recorded for inspection only."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.memory_optimize = True
        self.build_cuda_graph = False


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """reference: base/compiler.py CompiledProgram — wraps a Program with a
    BuildStrategy. Executor.run accepts it transparently."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


def cpu_places(device_count=None):
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    from ..core.device import CPUPlace
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..core.device import CUDAPlace
    ids = device_ids if device_ids is not None else range(len(jax.devices()))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


@contextlib.contextmanager
def device_guard(device=None):
    """reference: static/device_guard — pin ops to a device. Maps to
    jax.default_device for the guarded region."""
    if device in (None, "cpu"):
        dev = jax.devices("cpu")[0] if device == "cpu" else None
    else:
        idx = int(device.split(":")[1]) if ":" in str(device) else 0
        devs = jax.devices()
        dev = devs[min(idx, len(devs) - 1)]
    if dev is None:
        yield
    else:
        with jax.default_device(dev):
            yield


# ---------------------------------------------------------------------------
# Debug / host-callback ops
# ---------------------------------------------------------------------------

def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """reference: static/nn/control_flow.py Print op — passthrough + host print."""
    v = np.asarray(input._value)
    parts = [message or ""]
    if print_tensor_name and input.name:
        parts.append(f"name: {input.name}")
    if print_tensor_shape:
        parts.append(f"shape: {list(v.shape)}")
    if print_tensor_type:
        parts.append(f"dtype: {v.dtype}")
    flat = v.ravel() if summarize < 0 else v.ravel()[:summarize]
    parts.append(f"data: {flat}")
    print("  ".join(p for p in parts if p))
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host python op with optional custom backward (reference:
    static/nn/common.py py_func → py_func op). Eager: runs on host values and
    re-enters autograd through PyLayer when backward_func is given."""
    from ..autograd import PyLayer
    xs = x if isinstance(x, (list, tuple)) else [x]

    if backward_func is None:
        vals = func(*[np.asarray(t._value) for t in xs])
        vals = vals if isinstance(vals, (list, tuple)) else [vals]
        outs = out if isinstance(out, (list, tuple)) else [out]
        results = []
        for o, v in zip(outs, vals):
            t = Tensor(jnp.asarray(v), stop_gradient=True)
            t.name = getattr(o, "name", None)
            results.append(t)
        return results[0] if not isinstance(out, (list, tuple)) else results

    class _PyFunc(PyLayer):
        @staticmethod
        def forward(ctx, *inputs):
            ctx.save_for_backward(*inputs)
            vals = func(*[np.asarray(t._value) for t in inputs])
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            outs2 = [Tensor(jnp.asarray(v)) for v in vals]
            return outs2[0] if len(outs2) == 1 else tuple(outs2)

        @staticmethod
        def backward(ctx, *grads):
            saved = ctx.saved_tensor()
            gvals = backward_func(
                *[np.asarray(t._value) for t in saved],
                *[np.asarray(g._value) for g in grads])
            gvals = gvals if isinstance(gvals, (list, tuple)) else [gvals]
            gts = [Tensor(jnp.asarray(g)) for g in gvals]
            return gts[0] if len(gts) == 1 else tuple(gts)

    return _PyFunc.apply(*xs)


# ---------------------------------------------------------------------------
# Metrics + EMA + weight-norm attr (reference: static/nn/metric.py,
# incubate ExponentialMovingAverage, WeightNormParamAttr)
# ---------------------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """Batch AUC (reference: static/nn/metric.py auc). Returns
    (auc_out, [stat_pos, stat_neg]) like the static op's main outputs."""
    from ..metric import Auc
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    pred = np.asarray(input._value)
    if pred.ndim == 2 and pred.shape[1] >= 2:
        # (N, C) softmax: column 1 is the positive-class probability (same
        # convention as metric.Auc.update and the reference auc op)
        preds2 = pred[:, :2] if pred.shape[1] == 2 else \
            np.stack([1 - pred[:, 1], pred[:, 1]], axis=1)
    else:
        p1 = pred.reshape(-1)
        preds2 = np.stack([1 - p1, p1], axis=1)
    m.update(preds=preds2, labels=np.asarray(label._value).reshape(-1, 1))
    val = Tensor(jnp.asarray(m.accumulate(), jnp.float64))
    return val, [Tensor(jnp.asarray(m._stat_pos)), Tensor(jnp.asarray(m._stat_neg))]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """reference: static/nn/metric.py ctr_metric_bundle — local CTR stats:
    (mean positive rate, mean prediction, batch size)."""
    pred = np.asarray(input._value).reshape(-1)
    lab = np.asarray(label._value).reshape(-1)
    sq = float(np.mean((pred - lab) ** 2))
    return (Tensor(jnp.asarray(sq)),
            Tensor(jnp.asarray(float(pred.mean()))),
            Tensor(jnp.asarray(float(lab.size))))


class ExponentialMovingAverage:
    """EMA of trainable parameters with apply/restore swap (reference:
    static/ema.py ExponentialMovingAverage; thres_steps ramps the decay)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._thres_steps = thres_steps
        self._step = 0
        self._ema = {}
        self._backup = {}
        self._params = {}

    def _tracked(self, parameters=None):
        if parameters is not None:
            return {(p.name or str(id(p))): p for p in parameters}
        prog = default_main_program()
        return {k: p for k, p in getattr(prog, "_parameters", {}).items()
                if not p.stop_gradient}

    def update(self, parameters=None):
        self._step += 1
        decay = self._decay
        if self._thres_steps is not None:
            decay = min(self._decay, (1 + self._step) / (10 + self._step))
        params = self._tracked(parameters)
        self._params.update(params)
        for k, p in params.items():
            v = np.asarray(p._value, np.float32)
            if k not in self._ema:
                self._ema[k] = v.copy()
            else:
                self._ema[k] = decay * self._ema[k] + (1 - decay) * v

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for k, p in self._params.items():
            self._backup[k] = p._value
            if k in self._ema:
                p._value = jnp.asarray(self._ema[k], p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for k, p in self._params.items():
            if k in self._backup:
                p._value = self._backup[k]
        self._backup = {}


class WeightNormParamAttr:
    """reference: static/param_attr.py WeightNormParamAttr — declares
    weight-norm reparameterization (g * v/|v|) on a created parameter. Our
    layers apply it via nn.utils.weight_norm; this attr carries the config."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


# ---------------------------------------------------------------------------
# IPU stubs: exist for API parity, raise like a build without IPU support
# ---------------------------------------------------------------------------

def _no_ipu(*a, **k):
    raise RuntimeError("Can not use this function since PaddlePaddle is not "
                       "compiled with IPU")


class IpuStrategy:
    def __init__(self):
        _no_ipu()


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        _no_ipu()


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    _no_ipu()
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):
    _no_ipu()
